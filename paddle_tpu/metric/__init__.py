"""Metrics — ``paddle.metric`` equivalent.

Reference: ``python/paddle/metric/metrics.py`` (Metric base, Accuracy,
Precision, Recall, Auc). Accumulation is host-side numpy (metrics are not in
the jitted step; the step returns the raw correctness counts).
"""

from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(pred, label, k: int = 1):
    """Top-k accuracy of a batch (jit-friendly; reference
    ``operators/metrics/accuracy_op.cu``)."""
    import jax.numpy as jnp
    topk = jnp.argsort(pred, axis=-1)[..., -k:]
    hit = jnp.any(topk == label[..., None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


class Metric:
    def name(self) -> str:
        return type(self).__name__.lower()

    def reset(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def update(self, *args):  # pragma: no cover - abstract
        raise NotImplementedError

    def accumulate(self):  # pragma: no cover - abstract
        raise NotImplementedError


class Accuracy(Metric):
    def __init__(self, topk: int = 1):
        self.topk = topk
        self.reset()

    def reset(self):
        self._correct = 0
        self._total = 0

    def update(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(pred.shape[0], -1)[:, 0]
        topk = np.argsort(pred, axis=-1)[:, -self.topk:]
        hit = (topk == label[:, None]).any(axis=-1)
        self._correct += int(hit.sum())
        self._total += len(hit)
        return hit.mean()

    def accumulate(self) -> float:
        return self._correct / max(self._total, 1)


class Precision(Metric):
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.reset()

    def reset(self):
        self._tp = 0
        self._fp = 0

    def update(self, pred, label):
        pred = np.asarray(pred).reshape(-1) > self.threshold
        label = np.asarray(label).reshape(-1).astype(bool)
        self._tp += int((pred & label).sum())
        self._fp += int((pred & ~label).sum())

    def accumulate(self) -> float:
        return self._tp / max(self._tp + self._fp, 1)


class Recall(Metric):
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.reset()

    def reset(self):
        self._tp = 0
        self._fn = 0

    def update(self, pred, label):
        pred = np.asarray(pred).reshape(-1) > self.threshold
        label = np.asarray(label).reshape(-1).astype(bool)
        self._tp += int((pred & label).sum())
        self._fn += int((~pred & label).sum())

    def accumulate(self) -> float:
        return self._tp / max(self._tp + self._fn, 1)


class Auc(Metric):
    """Histogram-bucket AUC (reference ``metrics.py`` Auc /
    ``operators/metrics/auc_op``)."""

    def __init__(self, num_thresholds: int = 4095):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, pred, label):
        pred = np.asarray(pred)
        if pred.ndim == 2 and pred.shape[1] == 2:
            pred = pred[:, 1]
        pred = pred.reshape(-1)
        label = np.asarray(label).reshape(-1)
        idx = np.clip((pred * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[label > 0.5], 1)
        np.add.at(self._neg, idx[label <= 0.5], 1)

    def accumulate(self) -> float:
        tot_pos = self._pos[::-1].cumsum()[::-1]
        tot_neg = self._neg[::-1].cumsum()[::-1]
        tp, fp = np.r_[tot_pos, 0], np.r_[tot_neg, 0]
        auc = np.sum((fp[:-1] - fp[1:]) * (tp[:-1] + tp[1:]) / 2.0)
        denom = tot_pos[0] * tot_neg[0]
        return float(auc / denom) if denom > 0 else 0.0
