"""Probability distributions — the ``paddle.distribution`` surface.

Reference: ``python/paddle/distribution.py`` (Distribution base with
Uniform ``:168``, Normal ``:390``, Categorical ``:640``). TPU-native
formulation: sampling uses explicit ``jax.random`` keys (the reference's
int ``seed`` argument is accepted and folded into a key for parity, but
passing ``key=`` is the idiomatic path); all math is pure jnp so every
method jits, vmaps, and differentiates.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import rng as _rng

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _key(seed, key):
    if key is not None:
        return key
    if seed:
        return jax.random.PRNGKey(int(seed))
    return _rng.next_key()


class Distribution:
    """Abstract base (reference ``distribution.py:41``)."""

    def sample(self, shape=(), seed=0, *, key=None):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """Uniform on [low, high) (reference ``:168``); broadcastable
    low/high arrays supported."""

    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=(), seed=0, *, key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(seed, key), shape, jnp.float32)
        return self.low + u * (self.high - self.low)

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.float32)
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def probs(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        return jnp.log(self.high - self.low)


class Normal(Distribution):
    """Gaussian (reference ``:390``)."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), seed=0, *, key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        z = jax.random.normal(_key(seed, key), shape, jnp.float32)
        return self.loc + z * self.scale

    def log_prob(self, value):
        value = jnp.asarray(value, jnp.float32)
        var = jnp.square(self.scale)
        return (-jnp.square(value - self.loc) / (2.0 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2.0 * math.pi))

    def probs(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2.0 * math.pi) + jnp.log(self.scale)

    def kl_divergence(self, other: "Normal"):
        """KL(self || other) (reference ``:595``)."""
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Categorical(Distribution):
    """Categorical over the last axis of ``logits`` (reference
    ``:640``)."""

    def __init__(self, logits):
        self.logits = jnp.asarray(logits, jnp.float32)
        self._logp = jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs_all(self):
        return jnp.exp(self._logp)

    def sample(self, shape=(), seed=0, *, key=None):
        return jax.random.categorical(_key(seed, key), self.logits,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def entropy(self):
        # 0 * (-inf) = nan: masked categories (logit -inf, the standard
        # action-masking pattern) must contribute exactly 0
        p = jnp.exp(self._logp)
        return -jnp.sum(jnp.where(p > 0, p * self._logp, 0.0), axis=-1)

    def kl_divergence(self, other: "Categorical"):
        p = jnp.exp(self._logp)
        contrib = jnp.where(p > 0, p * (self._logp - other._logp), 0.0)
        return jnp.sum(contrib, axis=-1)

    def probs(self, value):
        """Probability of the given class indices (reference ``:862``)."""
        return jnp.exp(self.log_prob(value))

    def log_prob(self, value):
        value = jnp.asarray(value)
        return jnp.take_along_axis(self._logp, value[..., None],
                                   axis=-1)[..., 0]
