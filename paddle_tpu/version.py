__version__ = "1.0.0"
