"""FLOPs counting (reference ``python/paddle/hapi/dynamic_flops.py`` /
``static_flops.py``: per-layer hook-based multiply-add counters walking
the program).

TPU-native: XLA already computes an exact cost model for every compiled
executable — ``flops()`` compiles the forward and reads
``cost_analysis()['flops']``, which covers *every* op (fused, custom,
attention) rather than the hook-covered subset the reference counts.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

__all__ = ["flops"]


def flops(model_or_fn: Callable, *example_inputs: Any,
          per_sample: bool = False) -> int:
    """Analytical FLOPs of one forward pass at the example shapes."""
    fn = model_or_fn
    compiled = jax.jit(lambda *xs: fn(*xs)).lower(*example_inputs).compile()
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):  # older jax returns [dict]
        analysis = analysis[0]
    total = int(analysis.get("flops", 0))
    if per_sample:
        batch = example_inputs[0].shape[0]
        return total // max(batch, 1)
    return total
