"""FLOPs counting (reference ``python/paddle/hapi/dynamic_flops.py`` /
``static_flops.py``: per-layer hook-based multiply-add counters walking
the program).

TPU-native: XLA already computes an exact cost model for every compiled
executable — ``flops()`` compiles the forward and reads
``cost_analysis()['flops']``, which covers *every* op (fused, custom,
attention) rather than the hook-covered subset the reference counts.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

__all__ = ["flops"]


def flops(model_or_fn: Callable, *example_inputs: Any,
          per_sample: bool = False) -> int:
    """Analytical FLOPs of one forward pass at the example shapes."""
    fn = model_or_fn
    compiled = jax.jit(lambda *xs: fn(*xs)).lower(*example_inputs).compile()
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):  # older jax returns [dict]
        analysis = analysis[0]
    total = int(analysis.get("flops", 0))
    if per_sample:
        batch = example_inputs[0].shape[0]
        return total // max(batch, 1)
    return total


def summary(model, example_inputs=None) -> str:
    """Parameter table by module path (reference ``paddle.summary`` /
    ``hapi/model_summary.py``); returns the printed string."""
    import numpy as np

    from paddle_tpu.core.module import named_parameters

    rows = []
    total = 0
    trainable = 0
    from paddle_tpu.core.module import trainable_mask
    import jax

    mask_leaves = jax.tree_util.tree_leaves(trainable_mask(model))
    for (name, p), is_train in zip(named_parameters(model), mask_leaves):
        n = int(np.prod(p.shape)) if hasattr(p, "shape") else 1
        total += n
        if is_train:
            trainable += n
        rows.append((name, tuple(getattr(p, "shape", ())),
                     str(getattr(p, "dtype", "-")), n))
    w = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'Param':<{w}}{'Shape':<20}{'Dtype':<10}{'Count':>12}",
             "-" * (w + 42)]
    for name, shape, dtype, n in rows:
        lines.append(f"{name:<{w}}{str(shape):<20}{dtype:<10}{n:>12,}")
    lines.append("-" * (w + 42))
    lines.append(f"Total params: {total:,}  "
                 f"(trainable {trainable:,}, buffers {total - trainable:,})")
    if example_inputs is not None:
        lines.append(f"Forward FLOPs: {flops(model, *example_inputs):,}")
    out = "\n".join(lines)
    print(out)
    return out
