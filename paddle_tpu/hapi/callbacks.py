"""Training callbacks (reference ``python/paddle/hapi/callbacks.py``)."""

from __future__ import annotations

import sys
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRSchedulerCallback", "CallbackList"]


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference ProgBarLogger)."""

    def __init__(self, log_freq: int = 10):
        self.log_freq = log_freq

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        self._steps = 0

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self._steps % self.log_freq == 0:
            items = " ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, (int, float)))
            rate = self._steps / max(time.time() - self._t0, 1e-9)
            print(f"epoch {self._epoch} step {self._steps}: {items} "
                  f"({rate:.1f} steps/s)", file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        items = " ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                         if isinstance(v, (int, float)))
        print(f"epoch {epoch} done in {time.time()-self._t0:.1f}s {items}",
              file=sys.stderr)


class ModelCheckpoint(Callback):
    """Periodic checkpoint save (reference ModelCheckpoint)."""

    def __init__(self, save_dir: str, save_freq: int = 1):
        self.save_dir = save_dir
        self.save_freq = save_freq

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self.model.save_checkpoint(self.save_dir, step=epoch)


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", patience: int = 3,
                 mode: str = "min", min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.sign = 1.0 if mode == "min" else -1.0
        self.min_delta = min_delta
        self.best = float("inf")
        self.wait = 0
        self.stopped = False

    def on_epoch_end(self, epoch, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        score = self.sign * float(value)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True


class LRSchedulerCallback(Callback):
    """No-op placeholder for parity: schedules in this framework are pure
    functions of the step traced into the update (see optimizer.lr), so
    there is nothing to step on epoch end."""
