"""High-level Model API — ``paddle.Model`` (hapi) equivalent.

Reference: ``python/paddle/hapi/model.py:808`` (prepare ``:1241``,
fit ``:1296``, train_batch ``:895``; auto distributed context ``:165``).
The TPU version wraps the fleet strategy compiler: ``prepare`` builds the
jitted sharded train step (single-chip is just the degenerate mesh), and
``fit`` drives it from a DataLoader with callbacks/metrics.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import rng
from paddle_tpu.core.module import Module
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.hapi.callbacks import CallbackList, ProgBarLogger
from paddle_tpu.nn.common import call_layer

__all__ = ["Model"]


class Model:
    def __init__(self, network: Module, strategy: DistributedStrategy | None = None):
        self.network = network
        self.strategy = strategy or DistributedStrategy()
        self._step = None
        self._state = None
        self._loss = None
        self._metrics = []
        self._eval_jit = None

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics: Sequence | None = None):
        """Bind optimizer/loss/metrics and compile the train step
        (reference ``Model.prepare``)."""
        from paddle_tpu.distributed.fleet.strategy_compiler import (
            build_train_step,
        )
        from paddle_tpu.parallel.mesh import mesh_from_strategy

        self._loss = loss
        self._metrics = list(metrics or [])
        if optimizer is not None:
            mesh = mesh_from_strategy(self.strategy)

            def loss_fn(net, batch, training=True):
                # BN running stats ride the strategy compiler's state tape
                # (build_train_step opens it around this call)
                x, y = batch
                out = call_layer(net, x, training=training)
                return loss(out, y)

            self._step = build_train_step(
                self.network, optimizer, loss_fn=loss_fn,
                strategy=self.strategy, mesh=mesh)
            self._state = self._step.init_state(self.network)
        return self

    @property
    def network_live(self) -> Module:
        return self._state.model if self._state is not None else self.network

    # ------------------------------------------------------------------
    def train_batch(self, x, y):
        batch = (jnp.asarray(x), jnp.asarray(y))
        batch = self._step.shard_batch(batch)
        self._state, metrics = self._step(self._state, batch)
        return {k: float(v) for k, v in metrics.items()
                if jnp.ndim(v) == 0 and k != "all_finite"}

    def _shard_inputs(self, *arrs):
        """Place eval/predict inputs with the same batch sharding as the
        train step (VERDICT r1: an unsharded eval input would silently
        replicate on a multi-chip mesh). Params need no handling — they
        already carry their training NamedShardings, which jit respects."""
        arrs = tuple(jnp.asarray(a) for a in arrs)
        # _data_spec_fn identifies the flat CompiledTrainStep layout (the
        # LocalSGD step's shard_batch reshapes to a replica axis instead)
        if self._step is not None and hasattr(self._step, "_data_spec_fn"):
            return self._step.shard_batch(arrs)
        return arrs

    def eval_batch(self, x, y):
        if self._eval_jit is None:
            loss = self._loss

            @jax.jit
            def eval_fn(net, x, y):
                out = call_layer(net, x, training=False)
                return out, loss(out, y) if loss else jnp.zeros(())

            self._eval_jit = eval_fn
        x, y = self._shard_inputs(x, y)
        out, l = self._eval_jit(self.network_live, x, y)
        return out, float(l)

    def predict_batch(self, x):
        if not hasattr(self, "_pred_jit") or self._pred_jit is None:
            @jax.jit
            def pred(net, x):
                return call_layer(net, x, training=False)
            self._pred_jit = pred
        (x,) = self._shard_inputs(x)
        return self._pred_jit(self.network_live, x)

    # ------------------------------------------------------------------
    def fit(self, train_data, eval_data=None, epochs: int = 1,
            callbacks: Sequence | None = None, log_freq: int = 10,
            verbose: int = 1):
        """Train from a DataLoader (reference ``Model.fit:1296``)."""
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq))
        cblist = CallbackList(cbs, self)
        cblist.on_train_begin()
        history = []
        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            logs = {}
            for step_idx, batch in enumerate(train_data):
                x, y = batch
                logs = self.train_batch(x, y)
                cblist.on_train_batch_end(step_idx, logs)
            if eval_data is not None:
                logs.update(self.evaluate(eval_data, verbose=0))
            cblist.on_epoch_end(epoch, logs)
            history.append(logs)
            if any(getattr(c, "stopped", False) for c in cbs):
                break
        cblist.on_train_end()
        return history

    def evaluate(self, eval_data, verbose: int = 0) -> dict:
        for m in self._metrics:
            m.reset()
        total_loss, batches = 0.0, 0
        for x, y in eval_data:
            out, l = self.eval_batch(x, y)
            total_loss += l
            batches += 1
            for m in self._metrics:
                m.update(np.asarray(out), np.asarray(y))
        logs = {"eval_loss": total_loss / max(batches, 1)}
        for m in self._metrics:
            logs[f"eval_{m.name()}"] = m.accumulate()
        return logs

    def predict(self, test_data):
        outs = []
        for batch in test_data:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            outs.append(np.asarray(self.predict_batch(x)))
        return np.concatenate(outs, axis=0)

    # ------------------------------------------------------------------
    def save(self, path: str):
        from paddle_tpu.io import save_state_dict

        save_state_dict(self.network_live, path)

    def load(self, path: str):
        from paddle_tpu.io import load_state_dict

        net = load_state_dict(self.network_live, path)
        if self._state is not None:
            self._state = self._state._replace(model=net)
        else:
            self.network = net
        return self

    def save_checkpoint(self, directory: str, step: int):
        from paddle_tpu.io import save_checkpoint

        save_checkpoint(self._state, directory, step)

    def load_checkpoint(self, directory: str, step: int | None = None):
        from paddle_tpu.io import load_checkpoint

        self._state = load_checkpoint(self._state, directory, step)
        return self

    def summary(self) -> str:
        """Per-parameter table (delegates to the real ``paddle.summary``
        implementation in ``hapi/flops.py`` rather than duplicating it)."""
        from paddle_tpu.hapi.flops import summary

        return summary(self.network)
