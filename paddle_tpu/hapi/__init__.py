"""paddle_tpu.hapi — high-level Keras-like training API.

Reference: ``python/paddle/hapi/model.py:808`` (Model.fit/prepare/
evaluate/predict, callbacks, progbar).
"""

from paddle_tpu.hapi.callbacks import (
    Callback, EarlyStopping, LRSchedulerCallback, ModelCheckpoint,
    ProgBarLogger,
)
from paddle_tpu.hapi.model import Model
from paddle_tpu.hapi.flops import flops, summary  # noqa: E402
