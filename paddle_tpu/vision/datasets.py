"""Vision datasets (reference ``python/paddle/vision/datasets/``).

Zero-egress environment: MNIST reads the standard IDX files from a local
directory if present; ``RandomImageDataset`` provides deterministic
synthetic data for tests/smoke training (the role of the reference's
``paddle.dataset.common`` fake data helpers).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.data.dataset import Dataset

__all__ = ["MNIST", "RandomImageDataset"]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


class MNIST(Dataset):
    """MNIST from local IDX files (``train-images-idx3-ubyte[.gz]`` etc. in
    ``root``). No download — zero-egress environment."""

    def __init__(self, root: str, mode: str = "train", transform=None,
                 normalize: bool = True):
        prefix = "train" if mode == "train" else "t10k"
        imgs = labels = None
        for suffix in ("", ".gz"):
            ip = os.path.join(root, f"{prefix}-images-idx3-ubyte{suffix}")
            lp = os.path.join(root, f"{prefix}-labels-idx1-ubyte{suffix}")
            if os.path.exists(ip) and os.path.exists(lp):
                imgs, labels = _read_idx(ip), _read_idx(lp)
                break
        if imgs is None:
            raise FileNotFoundError(
                f"MNIST idx files not found under {root!r} (no download in "
                "this environment; place train-images-idx3-ubyte[.gz] there)")
        self.images = imgs.astype(np.float32)[:, None]  # [N, 1, 28, 28]
        if normalize:
            self.images = self.images / 127.5 - 1.0
        self.labels = labels.astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class RandomImageDataset(Dataset):
    """Deterministic synthetic labeled images for tests and smoke runs."""

    def __init__(self, num_samples: int = 256, image_shape=(1, 28, 28),
                 num_classes: int = 10, seed: int = 0, separable: bool = True):
        rs = np.random.RandomState(seed)
        self.labels = rs.randint(0, num_classes, num_samples).astype(np.int64)
        self.images = rs.randn(num_samples, *image_shape).astype(np.float32)
        if separable:
            # plant a class-dependent signal so models can actually learn;
            # signals depend only on (seed, class) so train/val splits with
            # different sizes share them
            rs_sig = np.random.RandomState(seed + 99991)
            for c in range(num_classes):
                mask = self.labels == c
                sig = rs_sig.randn(*image_shape).astype(np.float32)
                self.images[mask] += 2.0 * sig
        self.num_classes = num_classes

    def __getitem__(self, idx):
        return self.images[idx], self.labels[idx]

    def __len__(self):
        return len(self.images)
