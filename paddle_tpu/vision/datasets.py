"""Vision datasets (reference ``python/paddle/vision/datasets/``).

Zero-egress environment: MNIST reads the standard IDX files from a local
directory if present; ``RandomImageDataset`` provides deterministic
synthetic data for tests/smoke training (the role of the reference's
``paddle.dataset.common`` fake data helpers).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.data.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012",
           "RandomImageDataset"]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


class MNIST(Dataset):
    """MNIST from local IDX files (``train-images-idx3-ubyte[.gz]`` etc. in
    ``root``). No download — zero-egress environment."""

    def __init__(self, root: str, mode: str = "train", transform=None,
                 normalize: bool = True):
        prefix = "train" if mode == "train" else "t10k"
        imgs = labels = None
        for suffix in ("", ".gz"):
            ip = os.path.join(root, f"{prefix}-images-idx3-ubyte{suffix}")
            lp = os.path.join(root, f"{prefix}-labels-idx1-ubyte{suffix}")
            if os.path.exists(ip) and os.path.exists(lp):
                imgs, labels = _read_idx(ip), _read_idx(lp)
                break
        if imgs is None:
            raise FileNotFoundError(
                f"MNIST idx files not found under {root!r} (no download in "
                "this environment; place train-images-idx3-ubyte[.gz] there)")
        self.images = imgs.astype(np.float32)[:, None]  # [N, 1, 28, 28]
        if normalize:
            self.images = self.images / 127.5 - 1.0
        self.labels = labels.astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class RandomImageDataset(Dataset):
    """Deterministic synthetic labeled images for tests and smoke runs."""

    def __init__(self, num_samples: int = 256, image_shape=(1, 28, 28),
                 num_classes: int = 10, seed: int = 0, separable: bool = True):
        rs = np.random.RandomState(seed)
        self.labels = rs.randint(0, num_classes, num_samples).astype(np.int64)
        self.images = rs.randn(num_samples, *image_shape).astype(np.float32)
        if separable:
            # plant a class-dependent signal so models can actually learn;
            # signals depend only on (seed, class) so train/val splits with
            # different sizes share them
            rs_sig = np.random.RandomState(seed + 99991)
            for c in range(num_classes):
                mask = self.labels == c
                sig = rs_sig.randn(*image_shape).astype(np.float32)
                self.images[mask] += 2.0 * sig
        self.num_classes = num_classes

    def __getitem__(self, idx):
        return self.images[idx], self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """Same idx wire format as MNIST (reference
    ``vision/datasets/mnist.py`` FashionMNIST subclass); point ``root``
    at the fashion-mnist idx files."""


class Cifar10(Dataset):
    """CIFAR-10 from the python-version tar.gz (reference
    ``vision/datasets/cifar.py``): pickled batches of
    {data: [N, 3072] uint8, labels}. No download (zero egress)."""

    _PREFIXES = ("data_batch", "test_batch")
    _LABEL_KEYS = (b"labels", "labels")

    def __init__(self, data_file: str, mode: str = "train",
                 transform=None, backend: str = "cv2"):
        import pickle
        import tarfile

        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"Cifar data_file {data_file!r} not found (no download "
                "in this zero-egress environment)")
        want = self._PREFIXES[0] if mode == "train" else self._PREFIXES[1]
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                base = os.path.basename(member.name)
                if not base.startswith(want):
                    continue
                batch = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                data = batch[b"data"] if b"data" in batch else batch["data"]
                labs = None
                for k in self._LABEL_KEYS:
                    if k in batch:
                        labs = batch[k]
                        break
                images.append(np.asarray(data, np.uint8))
                labels.extend(labs)
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _PREFIXES = ("train", "test")
    _LABEL_KEYS = (b"fine_labels", "fine_labels")


def _default_image_loader(path: str) -> np.ndarray:
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (reference
    ``vision/datasets/folder.py``): ``root/class_x/xxx.ext``. The image
    decoder is pluggable; defaults to PIL (npy files load directly)."""

    EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")

    def __init__(self, root: str, loader=None, extensions=None,
                 transform=None):
        self.loader = loader or _default_image_loader
        self.transform = transform
        exts = tuple(extensions or self.EXTS)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Oxford-102 flowers (reference ``vision/datasets/flowers.py``):
    image tgz + scipy .mat labels/setids, all local paths."""

    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file: str, label_file: str, setid_file: str,
                 mode: str = "train", transform=None):
        import tarfile

        from scipy.io import loadmat

        for p in (data_file, label_file, setid_file):
            if not os.path.exists(p):
                raise FileNotFoundError(f"{p!r} not found (no download)")
        labels = loadmat(label_file)["labels"][0]
        ids = loadmat(setid_file)[self._SPLIT_KEY[mode]][0]
        self._wanted = {f"image_{i:05d}.jpg": int(labels[i - 1]) - 1
                        for i in ids}
        self._tar_path = data_file
        with tarfile.open(data_file) as tf:
            self._members = [m.name for m in tf.getmembers()
                             if os.path.basename(m.name) in self._wanted]
        self.transform = transform

    def __getitem__(self, idx):
        import io
        import tarfile

        from PIL import Image

        name = self._members[idx]
        with tarfile.open(self._tar_path) as tf:
            data = tf.extractfile(name).read()
        img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self._wanted[os.path.basename(name)])

    def __len__(self):
        return len(self._members)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (reference
    ``vision/datasets/voc2012.py``): returns (image, label_mask) from the
    local VOCtrainval tar."""

    def __init__(self, data_file: str, mode: str = "train",
                 transform=None):
        import tarfile

        if not os.path.exists(data_file):
            raise FileNotFoundError(f"{data_file!r} not found (no download)")
        self._tar_path = data_file
        want = {"train": "train.txt", "valid": "val.txt",
                "test": "val.txt"}[mode]
        with tarfile.open(data_file) as tf:
            names = {m.name for m in tf.getmembers()}
            seg_list = next(n for n in names
                            if n.endswith(f"Segmentation/{want}"))
            ids = tf.extractfile(seg_list).read().decode().split()
            self._pairs = []
            for i in ids:
                img = next((n for n in names
                            if n.endswith(f"JPEGImages/{i}.jpg")), None)
                msk = next((n for n in names
                            if n.endswith(f"SegmentationClass/{i}.png")),
                           None)
                if img and msk:
                    self._pairs.append((img, msk))
        self.transform = transform

    def __getitem__(self, idx):
        import io
        import tarfile

        from PIL import Image

        img_name, msk_name = self._pairs[idx]
        with tarfile.open(self._tar_path) as tf:
            img = np.asarray(Image.open(io.BytesIO(
                tf.extractfile(img_name).read())).convert("RGB"))
            mask = np.asarray(Image.open(io.BytesIO(
                tf.extractfile(msk_name).read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask.astype(np.int64)

    def __len__(self):
        return len(self._pairs)
