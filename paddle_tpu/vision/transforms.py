"""Image transforms (reference ``python/paddle/vision/transforms``) —
numpy host-side ops composed by ``Compose``."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "RandomCrop",
           "RandomHorizontalFlip", "ToCHW", "CenterCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class ToCHW:
    def __call__(self, img):
        img = np.asarray(img)
        return img.transpose(2, 0, 1) if img.ndim == 3 else img


class Resize:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        # nearest-neighbour host resize (keeps zero deps)
        c, h, w = img.shape
        oh, ow = self.size
        yi = (np.arange(oh) * h // oh).clip(0, h - 1)
        xi = (np.arange(ow) * w // ow).clip(0, w - 1)
        return img[:, yi][:, :, xi]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        c, h, w = img.shape
        th, tw = self.size
        top, left = (h - th) // 2, (w - tw) // 2
        return img[:, top:top + th, left:left + tw]


class RandomCrop:
    def __init__(self, size, padding: int = 0, seed: int = 0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.rs = np.random.RandomState(seed)

    def __call__(self, img):
        if self.padding:
            img = np.pad(img, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)))
        c, h, w = img.shape
        th, tw = self.size
        top = self.rs.randint(0, h - th + 1)
        left = self.rs.randint(0, w - tw + 1)
        return img[:, top:top + th, left:left + tw]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5, seed: int = 0):
        self.prob = prob
        self.rs = np.random.RandomState(seed)

    def __call__(self, img):
        if self.rs.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img
