"""Image transforms (reference ``python/paddle/vision/transforms``) —
numpy host-side ops composed by ``Compose``."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "RandomCrop",
           "RandomHorizontalFlip", "ToCHW", "CenterCrop", "BaseTransform",
           "ToTensor", "Transpose", "Pad", "RandomVerticalFlip",
           "BrightnessTransform", "ContrastTransform", "SaturationTransform",
           "HueTransform", "ColorJitter", "Grayscale", "RandomRotation",
           "RandomResizedCrop", "resize"]


def _interp_axis(in_size: int, out_size: int):
    """Half-pixel source coordinates for one axis (the cv2 INTER_LINEAR /
    align_corners=False convention the reference's functional_cv2.resize
    inherits): src = (dst + 0.5) * in/out - 0.5, edges clamped."""
    src = (np.arange(out_size) + 0.5) * (in_size / out_size) - 0.5
    i0 = np.floor(src).astype(np.int64)
    frac = (src - i0).astype(np.float32)
    return (np.clip(i0, 0, in_size - 1), np.clip(i0 + 1, 0, in_size - 1),
            frac)


def resize(img, size, interpolation: str = "bilinear"):
    """Resize an HW / HWC numpy image (reference
    ``vision/transforms/functional.py:96``): int size = shorter edge
    scaled keeping aspect ratio, (h, w) = exact; bilinear (default, the
    reference default) or nearest interpolation. Integer inputs come back
    in their own dtype (rounded), floats stay float32."""
    arr = np.asarray(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if (w <= h and w == size) or (h <= w and h == size):
            return np.asarray(img)
        if w < h:
            oh, ow = int(size * h / w), size
        else:
            oh, ow = size, int(size * w / h)
    else:
        oh, ow = size
    if interpolation == "nearest":
        yi = (np.arange(oh) * h // oh).clip(0, h - 1)
        xi = (np.arange(ow) * w // ow).clip(0, w - 1)
        out = arr[yi][:, xi]
    elif interpolation == "bilinear":
        y0, y1, fy = _interp_axis(h, oh)
        x0, x1, fx = _interp_axis(w, ow)
        a = arr.astype(np.float32)
        fx = fx[None, :, None]
        top = a[y0][:, x0] * (1 - fx) + a[y0][:, x1] * fx
        bot = a[y1][:, x0] * (1 - fx) + a[y1][:, x1] * fx
        out = top * (1 - fy)[:, None, None] + bot * fy[:, None, None]
        if np.issubdtype(arr.dtype, np.integer):
            info = np.iinfo(arr.dtype)
            out = np.clip(np.rint(out), info.min, info.max).astype(arr.dtype)
    else:
        raise ValueError(
            f"interpolation {interpolation!r}: supported are 'bilinear' "
            "and 'nearest'")
    return out[:, :, 0] if squeeze else out


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class ToCHW:
    def __call__(self, img):
        img = np.asarray(img)
        return img.transpose(2, 0, 1) if img.ndim == 3 else img


class Resize:
    """CHW resize (this class predates the HWC new-style transforms and
    keeps CHW for the MNIST pipelines). Reference transforms.Resize: int
    size = shorter edge keeping aspect; bilinear by default."""

    def __init__(self, size, interpolation: str = "bilinear"):
        self.size = size if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, img):
        img = np.asarray(img)
        out = resize(img.transpose(1, 2, 0), self.size, self.interpolation)
        return out.transpose(2, 0, 1)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        c, h, w = img.shape
        th, tw = self.size
        top, left = (h - th) // 2, (w - tw) // 2
        return img[:, top:top + th, left:left + tw]


class RandomCrop:
    def __init__(self, size, padding: int = 0, seed: int = 0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.rs = np.random.RandomState(seed)

    def __call__(self, img):
        if self.padding:
            img = np.pad(img, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)))
        c, h, w = img.shape
        th, tw = self.size
        top = self.rs.randint(0, h - th + 1)
        left = self.rs.randint(0, w - tw + 1)
        return img[:, top:top + th, left:left + tw]


class RandomHorizontalFlip:
    def __init__(self, prob: float = 0.5, seed: int = 0):
        self.prob = prob
        self.rs = np.random.RandomState(seed)

    def __call__(self, img):
        if self.rs.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class BaseTransform:
    """Subclassing point for custom transforms (reference BaseTransform;
    the keys/data-structure plumbing of the reference collapses to plain
    ``__call__`` here)."""

    def __call__(self, img):  # pragma: no cover - abstract
        raise NotImplementedError


class ToTensor:
    """HWC uint8/float image → CHW float32 in [0, 1] (reference
    to_tensor)."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        img = img.transpose(2, 0, 1).astype(np.float32)
        if img.max() > 1.0:
            img = img / 255.0
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode: str = "constant"):
        if isinstance(padding, int):
            padding = (padding,) * 4
        self.padding = tuple(padding)  # left, top, right, bottom
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        l, t, r, b = self.padding
        img = np.asarray(img)
        pads = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
        if self.mode == "constant":
            return np.pad(img, pads, constant_values=self.fill)
        mode = {"reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[self.mode]
        return np.pad(img, pads, mode=mode)


class RandomVerticalFlip:
    def __init__(self, prob: float = 0.5):
        self.prob = float(prob)

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return img


class BrightnessTransform:
    """Scale brightness by U[max(0,1-v), 1+v] (reference semantics)."""

    def __init__(self, value: float):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return _clip_like(np.asarray(img, np.float32) * f, img)


class ContrastTransform:
    def __init__(self, value: float):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        arr = np.asarray(img, np.float32)
        mean = _gray(arr).mean()
        return _clip_like(mean + f * (arr - mean), img)


class SaturationTransform:
    def __init__(self, value: float):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        arr = np.asarray(img, np.float32)
        gray = _gray(arr)[..., None]
        return _clip_like(gray + f * (arr - gray), img)


class HueTransform:
    """Shift hue by U[-v, v] (v <= 0.5), via the HSV round trip the
    reference's cv2/PIL paths perform."""

    def __init__(self, value: float):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        import colorsys

        shift = np.random.uniform(-self.value, self.value)
        arr = np.asarray(img, np.float32)
        scale = 255.0 if arr.max() > 1.0 else 1.0
        rgb = arr / scale
        r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
        maxc = rgb.max(-1)
        minc = rgb.min(-1)
        v = maxc
        delta = maxc - minc
        s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
        # hue in [0,1)
        rc = np.where(delta > 0, (maxc - r) / np.maximum(delta, 1e-12), 0)
        gc = np.where(delta > 0, (maxc - g) / np.maximum(delta, 1e-12), 0)
        bc = np.where(delta > 0, (maxc - b) / np.maximum(delta, 1e-12), 0)
        h = np.where(maxc == r, bc - gc,
                     np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
        h = (h / 6.0) % 1.0
        h = (h + shift) % 1.0
        i = np.floor(h * 6.0)
        f = h * 6.0 - i
        p = v * (1.0 - s)
        q = v * (1.0 - s * f)
        t = v * (1.0 - s * (1.0 - f))
        i = i.astype(np.int32) % 6
        r2 = np.choose(i, [v, q, p, p, t, v])
        g2 = np.choose(i, [t, v, v, q, p, p])
        b2 = np.choose(i, [p, p, t, v, v, q])
        out = np.stack([r2, g2, b2], axis=-1) * scale
        return _clip_like(out, img)


class ColorJitter:
    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels: int = 1):
        self.num_output_channels = int(num_output_channels)

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        gray = _gray(arr)[..., None]
        out = np.repeat(gray, self.num_output_channels, axis=-1)
        return _clip_like(out, img)


class RandomRotation:
    def __init__(self, degrees):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)

    def __call__(self, img):
        from scipy import ndimage

        angle = np.random.uniform(*self.degrees)
        arr = np.asarray(img)
        out = ndimage.rotate(arr.astype(np.float32), angle,
                             axes=(0, 1), reshape=False, order=1)
        return _clip_like(out, img)


class RandomResizedCrop:
    """Random area/aspect crop then resize, HWC layout (reference
    RandomResizedCrop; the new-style transforms here follow the
    reference's PIL/cv2 HWC convention — ``Resize``/``CenterCrop`` above
    predate them and stay CHW for the MNIST pipelines)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation: str = "bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _resize_hwc(self, arr, size):
        return resize(arr, tuple(size), self.interpolation)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                crop = arr[top:top + ch, left:left + cw]
                return self._resize_hwc(crop, self.size)
        side = min(h, w)
        top, left = (h - side) // 2, (w - side) // 2
        return self._resize_hwc(arr[top:top + side, left:left + side],
                                self.size)


def _gray(arr):
    if arr.ndim == 3 and arr.shape[-1] == 3:
        return arr @ np.asarray([0.299, 0.587, 0.114], np.float32)
    return arr.reshape(arr.shape[:2] + (-1,)).mean(-1)


def _clip_like(arr, ref):
    ref = np.asarray(ref)
    if ref.dtype == np.uint8:
        return np.clip(arr, 0, 255).astype(np.uint8)
    return arr.astype(np.float32)
