"""Detection operator library — TPU-native (static-shape, masked) forms.

The reference implements these as per-box CPU/CUDA loops under
``paddle/fluid/operators/detection/``. Dynamic result sizes (NMS keeps a
variable number of boxes, proposals vary per image) don't exist on TPU —
every op here returns fixed-shape tensors with an explicit validity
encoding (label slot -1 / score 0 padding), which is also what makes
them jit/vmap/pmap-composable.

Implemented (reference file cited per function): yolo_box, prior_box,
anchor_generator, box_coder (encode/decode), box_clip, iou_similarity,
box_iou_xyxy, bipartite_match, matrix_nms, multiclass_nms, roi_align,
distance2bbox/bbox2distance (the anchor-free PP-YOLOE transforms),
generate_anchor_points, deform_conv2d (v1/v2, r4), psroi_pool (R-FCN
position-sensitive pooling as masked bin averages over static grids,
r4), prroi_pool (PrRoIPool's exact bilinear integral in separable
closed form, roi-coordinate-differentiable, r4).

Deliberately not ported: the RCNN proposal pipeline
(``generate_proposals_op.cc``, ``collect/distribute_fpn_proposals_op.cc``)
— subsumed by the anchor-free detectors this framework ships
(PP-YOLOE-class); and the polygon ops
(``polygon_box_transform_op.cc``, OCR-specific host-side geometry).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "yolo_box", "prior_box", "anchor_generator", "box_coder", "box_clip",
    "iou_similarity", "box_iou_xyxy", "bipartite_match", "matrix_nms",
    "multiclass_nms", "roi_align", "distance2bbox", "bbox2distance",
    "generate_anchor_points", "deform_conv2d", "psroi_pool", "prroi_pool",
    "generate_proposals", "density_prior_box", "target_assign",
    "distribute_fpn_proposals", "collect_fpn_proposals",
]


# ---------------------------------------------------------------------------
# box geometry
# ---------------------------------------------------------------------------

def box_iou_xyxy(boxes1, boxes2, normalized: bool = True):
    """Pairwise IoU for [..., M, 4] vs [..., N, 4] corner-format boxes →
    [..., M, N]. The +1 convention for unnormalized pixel boxes follows
    the reference (``detection/bbox_util.h`` JaccardOverlap)."""
    off = 0.0 if normalized else 1.0
    x1a, y1a, x2a, y2a = jnp.split(boxes1, 4, axis=-1)        # [..., M, 1]
    x1b, y1b, x2b, y2b = (t[..., None, :, 0]
                          for t in jnp.split(boxes2, 4, axis=-1))
    iw = jnp.clip(jnp.minimum(x2a, x2b) - jnp.maximum(x1a, x1b) + off,
                  0.0, None)
    ih = jnp.clip(jnp.minimum(y2a, y2b) - jnp.maximum(y1a, y1b) + off,
                  0.0, None)
    inter = iw * ih
    area_a = jnp.clip(x2a - x1a + off, 0.0, None) * \
        jnp.clip(y2a - y1a + off, 0.0, None)
    area_b = jnp.clip(x2b - x1b + off, 0.0, None) * \
        jnp.clip(y2b - y1b + off, 0.0, None)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized: bool = True):
    """[M, 4] × [N, 4] → [M, N] IoU (reference
    ``detection/iou_similarity_op.h``)."""
    return box_iou_xyxy(x, y, normalized=box_normalized)


def box_clip(boxes, img_size):
    """Clip [..., 4] xyxy boxes to an (h, w) image (reference
    ``detection/box_clip_op.h``: clamp to [0, dim-1])."""
    h, w = img_size[..., 0], img_size[..., 1]
    x1 = jnp.clip(boxes[..., 0], 0.0, w[..., None] - 1)
    y1 = jnp.clip(boxes[..., 1], 0.0, h[..., None] - 1)
    x2 = jnp.clip(boxes[..., 2], 0.0, w[..., None] - 1)
    y2 = jnp.clip(boxes[..., 3], 0.0, h[..., None] - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True):
    """Encode/decode boxes against priors (reference
    ``detection/box_coder_op.h``).

    encode: target [M, 4] against priors [N, 4] → [M, N, 4]
    decode: target [M, N(or 1 broadcast), 4] deltas + priors [N, 4] → [M, N, 4]
    """
    off = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + off                  # [N]
    ph = prior_box[:, 3] - prior_box[:, 1] + off
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), target_box.dtype)
        var = jnp.broadcast_to(var, prior_box.shape)
    elif prior_box_var.ndim == 1:
        var = jnp.broadcast_to(prior_box_var, prior_box.shape)
    else:
        var = prior_box_var                                       # [N, 4]

    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + off            # [M]
        th = target_box[:, 3] - target_box[:, 1] + off
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None]) / pw[None] / var[None, :, 0]
        dy = (tcy[:, None] - pcy[None]) / ph[None] / var[None, :, 1]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None], 1e-10)) \
            / var[None, :, 2]
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None], 1e-10)) \
            / var[None, :, 3]
        return jnp.stack([dx, dy, dw, dh], axis=-1)

    if code_type == "decode_center_size":
        t = target_box if target_box.ndim == 3 \
            else target_box[:, None, :]                           # [M, N, 4]
        cx = var[None, :, 0] * t[..., 0] * pw[None] + pcx[None]
        cy = var[None, :, 1] * t[..., 1] * ph[None] + pcy[None]
        w = jnp.exp(var[None, :, 2] * t[..., 2]) * pw[None]
        h = jnp.exp(var[None, :, 3] * t[..., 3]) * ph[None]
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)

    raise ValueError(f"unknown code_type {code_type!r}")


# ---------------------------------------------------------------------------
# anchors / priors
# ---------------------------------------------------------------------------

def anchor_generator(feature_shape, anchor_sizes, aspect_ratios, stride,
                     offset: float = 0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """Dense (H, W, A, 4) anchors in xyxy pixels (reference
    ``detection/anchor_generator_op.h`` AnchorGenerator kernel)."""
    H, W = feature_shape
    sx, sy = float(stride[0]), float(stride[1])
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * sx       # [W]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * sy       # [H]
    ws, hs = [], []
    for r in aspect_ratios:
        for s in anchor_sizes:
            # area = s², aspect = h/w = r (the reference's convention)
            w = s / math.sqrt(r)
            h = s * math.sqrt(r)
            ws.append(w)
            hs.append(h)
    w = jnp.asarray(ws, jnp.float32)                             # [A]
    h = jnp.asarray(hs, jnp.float32)
    anchors = jnp.stack([
        cx[None, :, None] - 0.5 * w[None, None, :]
        + jnp.zeros((H, 1, 1), jnp.float32),
        cy[:, None, None] - 0.5 * h[None, None, :]
        + jnp.zeros((1, W, 1), jnp.float32),
        cx[None, :, None] + 0.5 * w[None, None, :]
        + jnp.zeros((H, 1, 1), jnp.float32),
        cy[:, None, None] + 0.5 * h[None, None, :]
        + jnp.zeros((1, W, 1), jnp.float32),
    ], axis=-1)                                                  # [H, W, A, 4]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return anchors, var


def prior_box(feature_shape, image_shape, min_sizes, max_sizes=(),
              aspect_ratios=(1.0,), flip: bool = True, clip: bool = False,
              step=(0.0, 0.0), offset: float = 0.5,
              variances=(0.1, 0.1, 0.2, 0.2), min_max_aspect_ratios_order
              : bool = False):
    """SSD prior boxes, normalized xyxy (reference
    ``detection/prior_box_op.h`` — including the expanded-ratio order and
    the extra sqrt(min·max) prior)."""
    H, W = feature_shape
    img_h, img_w = image_shape
    step_w = float(step[1]) or img_w / W
    step_h = float(step[0]) or img_h / H

    ratios = [1.0]
    for r in aspect_ratios:
        if all(abs(r - e) > 1e-6 for e in ratios):
            ratios.append(r)
            if flip:
                ratios.append(1.0 / r)

    # per-min_size prior groups, interleaved max prior — matching the
    # reference's two orderings exactly (prior_box_op.h: ratios then
    # sqrt(min·max) by default; [min, max, other-ratios] when
    # min_max_aspect_ratios_order)
    whs = []
    for s_i, ms in enumerate(min_sizes):
        mx = max_sizes[s_i] if s_i < len(max_sizes) else None
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if mx is not None:
                sq = math.sqrt(ms * mx)
                whs.append((sq, sq))
            for r in ratios:
                if abs(r - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(r), ms / math.sqrt(r)))
        else:
            for r in ratios:
                whs.append((ms * math.sqrt(r), ms / math.sqrt(r)))
            if mx is not None:
                sq = math.sqrt(ms * mx)
                whs.append((sq, sq))

    w = jnp.asarray([p[0] for p in whs], jnp.float32) / img_w    # [A]
    h = jnp.asarray([p[1] for p in whs], jnp.float32) / img_h
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w / img_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h / img_h
    boxes = jnp.stack([
        cx[None, :, None] - 0.5 * w + jnp.zeros((H, 1, 1)),
        cy[:, None, None] - 0.5 * h + jnp.zeros((1, W, 1)),
        cx[None, :, None] + 0.5 * w + jnp.zeros((H, 1, 1)),
        cy[:, None, None] + 0.5 * h + jnp.zeros((1, W, 1)),
    ], axis=-1)                                                  # [H, W, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return boxes, var


def generate_anchor_points(feat_shapes: Sequence[tuple], strides,
                           offset: float = 0.5):
    """Anchor-free center points for multi-level heads: returns
    (points [L, 2] (x, y in pixels), stride_per_point [L, 1]) where L is
    the total number of locations across levels. The PP-YOLOE-class
    replacement for dense anchor enumeration."""
    pts, sts = [], []
    for (H, W), s in zip(feat_shapes, strides):
        xs = (jnp.arange(W, dtype=jnp.float32) + offset) * s
        ys = (jnp.arange(H, dtype=jnp.float32) + offset) * s
        gx, gy = jnp.meshgrid(xs, ys)
        pts.append(jnp.stack([gx.reshape(-1), gy.reshape(-1)], axis=-1))
        sts.append(jnp.full((H * W, 1), float(s), jnp.float32))
    return jnp.concatenate(pts), jnp.concatenate(sts)


def distance2bbox(points, distances):
    """(l, t, r, b) distances from center points → xyxy boxes."""
    x1 = points[..., 0] - distances[..., 0]
    y1 = points[..., 1] - distances[..., 1]
    x2 = points[..., 0] + distances[..., 2]
    y2 = points[..., 1] + distances[..., 3]
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def bbox2distance(points, bbox, max_dist: float | None = None):
    """xyxy boxes → (l, t, r, b) distances from points."""
    d = jnp.stack([
        points[..., 0] - bbox[..., 0], points[..., 1] - bbox[..., 1],
        bbox[..., 2] - points[..., 0], bbox[..., 3] - points[..., 1],
    ], axis=-1)
    if max_dist is not None:
        d = jnp.clip(d, 0.0, max_dist)
    return d


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float, downsample_ratio: int,
             clip_bbox: bool = True, scale_x_y: float = 1.0):
    """Decode YOLOv3 head output (reference ``detection/yolo_box_op.h``
    GetYoloBox/CalcDetectionBox/CalcLabelScore).

    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w) int.
    Returns (boxes [N, H*W*A, 4] xyxy in image pixels,
    scores [N, H*W*A, C]); predictions below conf_thresh are zeroed —
    the reference's variable-size filtering expressed as masking.
    """
    N, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    x = x.reshape(N, A, 5 + class_num, H, W)
    in_h = downsample_ratio * H
    in_w = downsample_ratio * W
    bias = -0.5 * (scale_x_y - 1.0)

    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]

    sx = jax.nn.sigmoid(x[:, :, 0]) * scale_x_y + bias      # [N, A, H, W]
    sy = jax.nn.sigmoid(x[:, :, 1]) * scale_x_y + bias
    cx = (grid_x + sx) * img_w / W
    cy = (grid_y + sy) * img_h / H
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] * img_w / in_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] * img_h / in_h

    x1, y1 = cx - bw / 2, cy - bh / 2
    x2, y2 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, None)
        y1 = jnp.clip(y1, 0.0, None)
        x2 = jnp.minimum(x2, img_w - 1.0)
        y2 = jnp.minimum(y2, img_h - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)            # [N, A, H, W, 4]

    conf = jax.nn.sigmoid(x[:, :, 4])                       # [N, A, H, W]
    keep = conf >= conf_thresh
    conf = jnp.where(keep, conf, 0.0)
    cls = jax.nn.sigmoid(x[:, :, 5:])                       # [N, A, C, H, W]
    scores = conf[:, :, None] * cls
    boxes = jnp.where(keep[..., None], boxes, 0.0)

    # flatten to (h·w·a) ordering like the reference's entry indexing
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(N, H * W * A, 4)
    scores = scores.transpose(0, 3, 4, 1, 2).reshape(N, H * W * A, class_num)
    return boxes, scores


# ---------------------------------------------------------------------------
# matching / NMS
# ---------------------------------------------------------------------------

def bipartite_match(similarity, valid_rows=None):
    """Greedy bipartite matching (reference
    ``detection/bipartite_match_op.cc`` BipartiteMatch): repeatedly take
    the globally best (row, col) pair, remove both. similarity [M, N]
    (rows = gt, cols = priors). Returns (match_indices [N] int32 — the
    matched row per column, -1 unmatched; match_dist [N])."""
    M, N = similarity.shape
    NEG = jnp.asarray(-1e9, similarity.dtype)
    if valid_rows is not None:
        similarity = jnp.where(valid_rows[:, None], similarity, NEG)

    def body(_, state):
        sim, idx, dist = state
        flat = jnp.argmax(sim)
        r, c = flat // N, flat % N
        best = sim[r, c]
        take = best > 0
        idx = jnp.where(take, idx.at[c].set(r.astype(jnp.int32)), idx)
        dist = jnp.where(take, dist.at[c].set(best), dist)
        # remove the row and column from further matching
        sim = jnp.where(take, sim.at[r, :].set(NEG).at[:, c].set(NEG), sim)
        return sim, idx, dist

    init = (similarity, jnp.full((N,), -1, jnp.int32),
            jnp.zeros((N,), similarity.dtype))
    _, idx, dist = lax.fori_loop(0, M, body, init)
    return idx, dist


def _greedy_nms_keep_sorted(b, s, iou_threshold: float,
                            normalized: bool = True, eta: float = 1.0):
    """Greedy NMS over score-descending candidates [K, 4]/[K] → bool
    keep [K]. Sequential like the reference (``detection/nms_util.h``
    NMSFast), expressed as a fori over the sorted candidates with a
    running suppression mask; ``eta < 1`` decays the adaptive IoU
    threshold after each kept box while it stays above 0.5 (NMSFast's
    ``adaptive_threshold *= eta``)."""
    K = b.shape[0]
    iou = box_iou_xyxy(b, b, normalized=normalized)          # [K, K]
    idx = jnp.arange(K)

    def body(i, state):
        keep, thr = state
        ki = keep[i]
        sup = (iou[i] > thr) & ki
        keep = keep & (~sup | (idx <= i))
        thr = jnp.where(ki & (eta < 1.0) & (thr > 0.5), thr * eta, thr)
        return keep, thr

    keep, _ = lax.fori_loop(
        0, K, body, (s > 0, jnp.asarray(iou_threshold, jnp.float32)))
    return keep


def multiclass_nms(bboxes, scores, score_threshold: float,
                   nms_top_k: int, keep_top_k: int,
                   nms_threshold: float = 0.3, normalized: bool = True,
                   background_label: int = -1, nms_eta: float = 1.0):
    """Class-aware NMS (reference ``detection/multiclass_nms_op.cc``
    MultiClassNMS kernel). bboxes [M, 4]; scores [C, M].

    Returns fixed-shape ``out [keep_top_k, 6]`` rows
    ``(label, score, x1, y1, x2, y2)`` with label = -1 padding, plus the
    valid-detection count — the LoD the reference emits, as a scalar.
    Batched use: ``jax.vmap``. Candidates are gathered to ``nms_top_k``
    *before* the IoU matrix, so cost is O(C·K²), not O(C·M²) (M can be
    10⁴ anchors; K is hundreds).
    """
    C, M = scores.shape
    k1 = min(nms_top_k, M) if nms_top_k > 0 else M

    def per_class(c_scores):
        s = jnp.where(c_scores >= score_threshold, c_scores, 0.0)
        top_s, top_i = lax.top_k(s, k1)          # sorted desc, [k1]
        keep = _greedy_nms_keep_sorted(bboxes[top_i], top_s, nms_threshold,
                                       normalized, nms_eta)
        return jnp.where(keep, top_s, 0.0), top_i

    cls_ids = jnp.arange(C)
    kept_scores, kept_idx = jax.vmap(per_class)(scores)      # [C, k1]
    if background_label >= 0:
        kept_scores = jnp.where(cls_ids[:, None] == background_label, 0.0,
                                kept_scores)

    flat = kept_scores.reshape(-1)                           # [C*k1]
    k = min(keep_top_k if keep_top_k > 0 else C * k1, C * k1)
    top_scores, top_flat = lax.top_k(flat, k)
    top_cls = (top_flat // k1).astype(jnp.float32)
    top_box = bboxes[kept_idx.reshape(-1)[top_flat]]
    valid = top_scores > 0
    out = jnp.concatenate([
        jnp.where(valid, top_cls, -1.0)[:, None],
        top_scores[:, None],
        jnp.where(valid[:, None], top_box, 0.0),
    ], axis=1)
    if k < keep_top_k:
        out = jnp.concatenate([
            out, jnp.tile(jnp.asarray([[-1., 0., 0., 0., 0., 0.]]),
                          (keep_top_k - k, 1))])
    return out, jnp.sum(valid.astype(jnp.int32))


def matrix_nms(bboxes, scores, score_threshold: float, post_threshold: float,
               nms_top_k: int, keep_top_k: int, use_gaussian: bool = False,
               gaussian_sigma: float = 2.0, normalized: bool = True,
               background_label: int = -1):
    """Matrix NMS (reference ``detection/matrix_nms_op.cc``): parallel
    soft-suppression via the decayed-IoU matrix — no sequential loop at
    all, the NMS formulation TPUs actually like. Same shapes/encoding as
    ``multiclass_nms``."""
    C, M = scores.shape
    k1 = min(nms_top_k if nms_top_k > 0 else M, M)

    def per_class(c_scores):
        s = jnp.where(c_scores >= score_threshold, c_scores, 0.0)
        top_s, top_i = lax.top_k(s, k1)                      # sorted desc
        b = bboxes[top_i]
        iou = box_iou_xyxy(b, b, normalized=normalized)      # [k1, k1]
        lower = jnp.tril(jnp.ones_like(iou), -1) > 0         # j < i
        tri = jnp.where(lower, iou, 0.0)                     # iou[i, j<i]
        # iou_max[j]: max IoU of j with boxes ranked above it
        comp = jnp.max(tri, axis=1)
        if use_gaussian:
            # reference decay_score<T, true>: exp((max² - iou²)·σ)
            decay = jnp.exp((comp[None, :] ** 2 - tri ** 2)
                            * gaussian_sigma)
        else:
            decay = (1.0 - tri) / jnp.maximum(1.0 - comp[None, :], 1e-10)
        dec = jnp.min(jnp.where(lower, decay, 1.0), axis=1)  # min over j<i
        # zero-score (padding) candidates must not survive
        out_s = jnp.where(top_s > 0, top_s * dec, 0.0)
        out_s = jnp.where(out_s >= post_threshold, out_s, 0.0)
        return out_s, top_i

    cls_scores, cls_idx = jax.vmap(per_class)(scores)        # [C, k1]
    if background_label >= 0:
        cls_scores = jnp.where(
            jnp.arange(C)[:, None] == background_label, 0.0, cls_scores)
    flat = cls_scores.reshape(-1)
    k = min(keep_top_k if keep_top_k > 0 else C * k1, C * k1)
    top_scores, top_flat = lax.top_k(flat, k)
    top_cls = (top_flat // k1).astype(jnp.float32)
    top_box = bboxes[cls_idx.reshape(-1)[top_flat]]
    valid = top_scores > 0
    out = jnp.concatenate([
        jnp.where(valid, top_cls, -1.0)[:, None],
        top_scores[:, None],
        jnp.where(valid[:, None], top_box, 0.0),
    ], axis=1)
    if k < keep_top_k:
        out = jnp.concatenate([
            out, jnp.tile(jnp.asarray([[-1., 0., 0., 0., 0., 0.]]),
                          (keep_top_k - k, 1))])
    return out, jnp.sum(valid.astype(jnp.int32))


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------

def roi_align(features, rois, roi_batch_idx, output_size,
              spatial_scale: float = 1.0, sampling_ratio: int = -1,
              aligned: bool = False):
    """RoIAlign (reference ``detection/roi_align_op.cc`` — bilinear
    sampling averaged over a fixed sample grid per output bin).

    features [N, C, H, W]; rois [R, 4] xyxy; roi_batch_idx [R] int.
    Static sampling: ``sampling_ratio`` must be > 0 on TPU (the
    adaptive ceil(roi/bin) of the reference is data-dependent); default
    -1 maps to 2, torchvision's common setting.
    """
    N, C, H, W = features.shape
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    sr = sampling_ratio if sampling_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    x1 = rois[:, 0] * spatial_scale - offset
    y1 = rois[:, 1] * spatial_scale - offset
    x2 = rois[:, 2] * spatial_scale - offset
    y2 = rois[:, 3] * spatial_scale - offset
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / pw                                        # [R]
    bin_h = roi_h / ph

    # sample coordinates: [R, ph(pw), sr] per axis
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    sy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
    ys = y1[:, None, None] + (iy[None, :, None] + sy[None, None, :]) \
        * bin_h[:, None, None]                                # [R, ph, sr]
    xs = x1[:, None, None] + (ix[None, :, None] + sy[None, None, :]) \
        * bin_w[:, None, None]                                # [R, pw, sr]

    def bilinear(feat, ys, xs):
        """feat [C, H, W]; ys [ph·sr]; xs [pw·sr] → [C, ph·sr, pw·sr]."""
        y = jnp.clip(ys, 0.0, H - 1.0)
        x = jnp.clip(xs, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        wy = y - y0
        wx = x - x0
        f00 = feat[:, y0][:, :, x0]                           # [C, Y, X]
        f01 = feat[:, y0][:, :, x1i]
        f10 = feat[:, y1i][:, :, x0]
        f11 = feat[:, y1i][:, :, x1i]
        wy = wy[None, :, None]
        wx = wx[None, None, :]
        # out-of-range samples contribute 0 (reference: empty when
        # y < -1 or y > H)
        ok_y = ((ys >= -1.0) & (ys <= H * 1.0))[None, :, None]
        ok_x = ((xs >= -1.0) & (xs <= W * 1.0))[None, None, :]
        val = (f00 * (1 - wy) * (1 - wx) + f01 * (1 - wy) * wx
               + f10 * wy * (1 - wx) + f11 * wy * wx)
        return jnp.where(ok_y & ok_x, val, 0.0)

    def per_roi(ys, xs, bidx):
        feat = features[bidx]                                 # [C, H, W]
        vals = bilinear(feat, ys.reshape(-1), xs.reshape(-1))
        vals = vals.reshape(C, ph, sr, pw, sr)
        return jnp.mean(vals, axis=(2, 4))                    # [C, ph, pw]

    return jax.vmap(per_roi)(ys, xs, roi_batch_idx)           # [R, C, ph, pw]


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None):
    """Deformable convolution v1/v2 (reference
    ``paddle/fluid/operators/deformable_conv_op.cu`` /
    ``deformable_conv_v1_op.cu``; API ``paddle.vision.ops.deform_conv2d``).

    The reference hand-writes a CUDA ``deformable_im2col`` that walks
    every output pixel; the TPU-native form is the same math as pure
    tensor ops — build the offset sampling grid, bilinear-gather the
    deformable im2col patches, and contract them with the weights on the
    MXU:

        out[b, o, y, x] = Σ_{c,k} w[o, c, k] ·
            bilinear(x[b, c], p0(y, x, k) + Δp[b, k, y, x]) (· m[b, k, y, x])

    ``x`` [B, Cin, H, W]; ``offset`` [B, 2·dg·K, Ho, Wo] ordered (dy, dx)
    per kernel tap (reference layout); optional v2 ``mask``
    [B, dg·K, Ho, Wo]; ``weight`` [Cout, Cin/groups, kh, kw]. With zero
    offsets and unit mask this is exactly ``F.conv2d`` (tested).
    Out-of-image samples read as zero, matching the CUDA kernel's
    bounds check.
    """
    from paddle_tpu.nn.functional import _amp_inputs

    # same AMP contract as the standard convs: inputs autocast to the
    # ambient dtype (the bilinear offsets/weights stay f32 — coordinates
    # are precision-sensitive and tiny)
    x, weight, bias = _amp_inputs("conv2d", x, weight, bias)
    B, Cin, H, W = x.shape
    Cout, Cin_g, kh, kw = weight.shape
    K = kh * kw
    dg = deformable_groups
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw_ = (dilation, dilation) if isinstance(dilation, int) else dilation
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw_ * (kw - 1) + 1)) // sw + 1
    if Cin % dg:
        raise ValueError(f"Cin={Cin} not divisible by "
                         f"deformable_groups={dg}")

    # base sampling positions p0 + kernel-tap displacement, per output
    # pixel and tap: [Ho, Wo, K]
    ys = jnp.arange(Ho) * sh - ph
    xs = jnp.arange(Wo) * sw - pw
    kyy, kxx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw_,
                            indexing="ij")
    base_y = ys[:, None, None] + kyy.reshape(-1)[None, None, :]
    base_x = xs[None, :, None] + kxx.reshape(-1)[None, None, :]

    off = offset.reshape(B, dg, K, 2, Ho, Wo)
    py = base_y[None, None] + off[:, :, :, 0].transpose(0, 1, 3, 4, 2)
    px = base_x[None, None] + off[:, :, :, 1].transpose(0, 1, 3, 4, 2)
    # py/px: [B, dg, Ho, Wo, K] float sample coordinates

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy = py - y0
    wx = px - x0

    def gather(chan_x, iy, ix):
        """chan_x [B, dg, Cg, H, W]; iy/ix [B, dg, Ho, Wo, K] int →
        samples [B, dg, Cg, Ho, Wo, K], zero outside the image."""
        valid = ((iy >= 0) & (iy < H) & (ix >= 0) & (ix < W))
        flat = (jnp.clip(iy, 0, H - 1) * W
                + jnp.clip(ix, 0, W - 1)).astype(jnp.int32)
        xf = chan_x.reshape(B, dg, -1, H * W)
        # vmap the per-(batch, group) gather; index arrays broadcast
        # over the channel dim
        g = jax.vmap(jax.vmap(
            lambda cx, ind: jnp.take(cx, ind.reshape(-1), axis=-1)
        ))(xf, flat)
        g = g.reshape(chan_x.shape[:3] + flat.shape[2:])
        return jnp.where(valid[:, :, None], g, 0.0)

    xg = x.reshape(B, dg, Cin // dg, H, W)
    y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
    v00 = gather(xg, y0i, x0i)
    v01 = gather(xg, y0i, x0i + 1)
    v10 = gather(xg, y0i + 1, x0i)
    v11 = gather(xg, y0i + 1, x0i + 1)
    # combine in the compute dtype so a bf16 autocast stays bf16 into
    # the einsum (f32 corner weights would promote everything back)
    wy_ = wy[:, :, None].astype(v00.dtype)
    wx_ = wx[:, :, None].astype(v00.dtype)
    samples = ((1 - wy_) * (1 - wx_) * v00 + (1 - wy_) * wx_ * v01
               + wy_ * (1 - wx_) * v10 + wy_ * wx_ * v11)
    if mask is not None:                         # v2 modulation
        m = mask.reshape(B, dg, K, Ho, Wo).transpose(0, 1, 3, 4, 2)
        samples = samples * m[:, :, None]

    # contract the deformable im2col with the weights on the MXU
    cols = samples.reshape(B, Cin, Ho, Wo, K)
    wmat = weight.reshape(groups, Cout // groups, Cin_g, K)
    cols_g = cols.reshape(B, groups, Cin // groups, Ho, Wo, K)
    out = jnp.einsum("bgchwk,gock->bgohw", cols_g, wmat,
                     preferred_element_type=jnp.float32)
    out = out.reshape(B, Cout, Ho, Wo).astype(x.dtype)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def psroi_pool(features, rois, roi_batch_idx, output_channels,
               output_size, spatial_scale: float = 1.0):
    """Position-sensitive RoI pooling (reference
    ``paddle/fluid/operators/psroi_pool_op.cc`` — the R-FCN head: input
    channel ``c·ph·pw + i·pw + j`` is average-pooled over output bin
    ``(i, j)`` of output channel ``c``).

    features [N, C, H, W] with C == output_channels·ph·pw; rois [R, 4]
    xyxy; roi_batch_idx [R] int. TPU-native form: the per-bin integer
    sub-rectangles of the reference's dynamic loops become boolean
    masks over the full [H, W] grid (static shapes), the channel
    grouping is a reshape, and the bin average is one einsum.
    Empty bins produce 0, matching the reference.
    """
    N, C, H, W = features.shape
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    if C != output_channels * ph * pw:
        raise ValueError(
            f"psroi_pool: C={C} must equal output_channels*ph*pw="
            f"{output_channels * ph * pw}")

    # reference rounds the roi to integer coords (C round(): half AWAY
    # from zero, not jnp.round's half-to-even), end = round(x2) + 1
    def _round_away(v):
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    x1 = _round_away(rois[:, 0]) * spatial_scale
    y1 = _round_away(rois[:, 1]) * spatial_scale
    x2 = (_round_away(rois[:, 2]) + 1.0) * spatial_scale
    y2 = (_round_away(rois[:, 3]) + 1.0) * spatial_scale
    roi_h = jnp.maximum(y2 - y1, 0.1)
    roi_w = jnp.maximum(x2 - x1, 0.1)
    bin_h = roi_h / ph                                        # [R]
    bin_w = roi_w / pw

    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    hs = jnp.clip(jnp.floor(iy[None] * bin_h[:, None] + y1[:, None]),
                  0, H)                                       # [R, ph]
    he = jnp.clip(jnp.ceil((iy[None] + 1) * bin_h[:, None] + y1[:, None]),
                  0, H)
    ws = jnp.clip(jnp.floor(ix[None] * bin_w[:, None] + x1[:, None]),
                  0, W)
    we = jnp.clip(jnp.ceil((ix[None] + 1) * bin_w[:, None] + x1[:, None]),
                  0, W)

    gy = jnp.arange(H, dtype=jnp.float32)
    gx = jnp.arange(W, dtype=jnp.float32)
    my = ((gy[None, None, :] >= hs[..., None])
          & (gy[None, None, :] < he[..., None]))              # [R, ph, H]
    mx = ((gx[None, None, :] >= ws[..., None])
          & (gx[None, None, :] < we[..., None]))              # [R, pw, W]

    grouped = features.reshape(N, output_channels, ph, pw, H, W)

    def per_roi(my_r, mx_r, bidx):
        # the bin mask is separable — contract the two 1-D masks
        # directly (no [ph, pw, H, W] intermediate)
        fy = my_r.astype(features.dtype)                      # [ph, H]
        fx = mx_r.astype(features.dtype)                      # [pw, W]
        total = jnp.einsum("cijhw,ih,jw->cij", grouped[bidx], fy, fx)
        count = (jnp.sum(fy, axis=1)[:, None]
                 * jnp.sum(fx, axis=1)[None, :])              # [ph, pw]
        return jnp.where(count > 0, total / jnp.maximum(count, 1.0), 0.0)

    return jax.vmap(per_roi)(my, mx, roi_batch_idx)  # [R, C_out, ph, pw]


def prroi_pool(features, rois, roi_batch_idx, output_size,
               spatial_scale: float = 1.0):
    """Precise RoI pooling (reference
    ``paddle/fluid/operators/prroi_pool_op.cc`` — PrRoIPool: the EXACT
    integral of the bilinearly-interpolated feature surface over each
    bin, no sampling grid, differentiable in the roi coordinates).

    TPU-native closed form: the bilinear surface is
    ``f(y, x) = Σ_{h,w} feat[h, w]·tri(y−h)·tri(x−w)`` (tri = the hat
    function), so its integral over a bin SEPARATES:
    ``∫∫ f = Σ_{h,w} feat[h, w]·Iy[h]·Ix[w]`` with
    ``Iy[h] = ∫ tri(y−h) dy`` in closed form — one [H] and one [W]
    weight vector per bin and a single einsum per roi, instead of the
    reference's per-cell ``PrRoIPoolingMatCalculation`` walk. Being a
    composition of smooth jnp ops, ``jax.grad`` provides both the
    feature gradient and the roi-coordinate gradient the reference
    hand-derives. Zero padding outside the feature map (official PrRoI
    semantics); degenerate (zero-area) bins produce 0.
    """
    N, C, H, W = features.shape
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    x1 = rois[:, 0] * spatial_scale
    y1 = rois[:, 1] * spatial_scale
    x2 = rois[:, 2] * spatial_scale
    y2 = rois[:, 3] * spatial_scale
    bin_h = (y2 - y1) / ph                                    # [R]
    bin_w = (x2 - x1) / pw

    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    ys = y1[:, None] + iy[None] * bin_h[:, None]              # [R, ph]
    ye = ys + bin_h[:, None]
    xs = x1[:, None] + ix[None] * bin_w[:, None]              # [R, pw]
    xe = xs + bin_w[:, None]

    def hat_integral(lo, hi, n):
        """∫_{lo}^{hi} tri(t − k) dt for every k in [0, n) — closed
        form via the hat antiderivative G (piecewise quadratic)."""
        k = jnp.arange(n, dtype=jnp.float32)

        def G(t):
            u = jnp.clip(t, -1.0, 1.0)
            return jnp.where(u <= 0, (u + 1.0) ** 2 / 2.0,
                             1.0 - (1.0 - u) ** 2 / 2.0)

        return G(hi[..., None] - k) - G(lo[..., None] - k)

    Iy = hat_integral(ys, ye, H)                              # [R, ph, H]
    Ix = hat_integral(xs, xe, W)                              # [R, pw, W]
    area = jnp.maximum(bin_h[:, None, None] * bin_w[:, None, None], 0.0)

    def per_roi(Iy_r, Ix_r, area_r, bidx):
        total = jnp.einsum("chw,ih,jw->cij", features[bidx], Iy_r, Ix_r)
        return jnp.where(area_r > 0.0, total / jnp.maximum(area_r, 1e-12),
                         0.0)

    return jax.vmap(per_roi)(Iy, Ix, area, roi_batch_idx)  # [R, C, ph, pw]


# ---------------------------------------------------------------------------
# two-stage detector ops: RPN proposals + FPN routing + assignment
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n: int = 6000, post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1):
    """RPN proposal generation for ONE image (reference
    ``detection/generate_proposals_op.cc`` / ``_v2``): decode anchor
    deltas, clip to the image, filter degenerate boxes, top-k before
    NMS, greedy NMS, top-k after. Fixed-shape: returns
    (rois [post_nms_top_n, 4], roi_scores [post_nms_top_n], valid mask)
    with suppressed slots zeroed — the jit-friendly replacement for the
    reference's variable-length LoD outputs.

    scores [A, H, W]; bbox_deltas [A*4, H, W]; anchors/variances
    [H, W, A, 4] (``anchor_generator`` layout).
    """
    A = scores.shape[0]
    s = scores.transpose(1, 2, 0).reshape(-1)                    # [HWA]
    d = bbox_deltas.reshape(A, 4, *bbox_deltas.shape[1:]) \
        .transpose(2, 3, 0, 1).reshape(-1, 4)                    # [HWA, 4]
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4)

    # decode (decode_center_size with per-anchor variances)
    aw = anc[:, 2] - anc[:, 0] + 1.0
    ah = anc[:, 3] - anc[:, 1] + 1.0
    acx = anc[:, 0] + 0.5 * aw
    acy = anc[:, 1] + 0.5 * ah
    cx = var[:, 0] * d[:, 0] * aw + acx
    cy = var[:, 1] * d[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(var[:, 2] * d[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(var[:, 3] * d[:, 3], 10.0)) * ah
    boxes = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                       cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=1)
    boxes = box_clip(boxes[None], jnp.asarray(im_shape,
                                              jnp.float32))[0]
    bw = boxes[:, 2] - boxes[:, 0] + 1.0
    bh = boxes[:, 3] - boxes[:, 1] + 1.0
    live = (bw >= min_size) & (bh >= min_size)
    s = jnp.where(live, s, -jnp.inf)

    k = min(pre_nms_top_n, s.shape[0])
    top_s, top_i = jax.lax.top_k(s, k)
    top_b = boxes[top_i]
    # RPN scores are raw logits (any sign): the NMS helper's keep-mask
    # init (s > 0) must see a positive surrogate for every live
    # candidate — suppression order comes from the sort, not magnitudes
    live_s = jnp.where(jnp.isfinite(top_s), 1.0, 0.0)
    keep = _greedy_nms_keep_sorted(top_b, live_s, nms_thresh,
                                   normalized=False)
    keep = keep & jnp.isfinite(top_s)
    final_s = jnp.where(keep, top_s, -jnp.inf)
    n_out = min(post_nms_top_n, k)
    out_s, oi = jax.lax.top_k(final_s, n_out)
    valid = jnp.isfinite(out_s)
    rois = jnp.where(valid[:, None], top_b[oi], 0.0)
    return rois, jnp.where(valid, out_s, 0.0), valid


def density_prior_box(input_hw, image_hw, densities, fixed_sizes,
                      fixed_ratios, step=None, offset: float = 0.5):
    """Density prior boxes (reference
    ``detection/density_prior_box_op.cc``): per feature-map cell, a
    densified grid of priors per (density, fixed_size) pair crossed
    with ``fixed_ratios``. Returns [H, W, P, 4] normalized xyxy."""
    fh, fw = input_hw
    ih, iw = image_hw
    sw = (iw / fw) if step is None else step[0]
    sh = (ih / fh) if step is None else step[1]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * sh
    boxes = []
    for density, fs in zip(densities, fixed_sizes):
        for ratio in fixed_ratios:
            bw = fs * math.sqrt(ratio)
            bh = fs / math.sqrt(ratio)
            step_d = fs / density
            for di in range(density):
                for dj in range(density):
                    ox = -fs / 2.0 + step_d / 2.0 + dj * step_d
                    oy = -fs / 2.0 + step_d / 2.0 + di * step_d
                    x0 = (cx[None, :] + ox - bw / 2.0) / iw
                    y0 = (cy[:, None] + oy - bh / 2.0) / ih
                    x1 = (cx[None, :] + ox + bw / 2.0) / iw
                    y1 = (cy[:, None] + oy + bh / 2.0) / ih
                    boxes.append(jnp.stack(
                        [jnp.broadcast_to(x0, (fh, fw)),
                         jnp.broadcast_to(y0, (fh, fw)),
                         jnp.broadcast_to(x1, (fh, fw)),
                         jnp.broadcast_to(y1, (fh, fw))], axis=-1))
    return jnp.clip(jnp.stack(boxes, axis=2), 0.0, 1.0)


def target_assign(x, match_indices, mismatch_value=0.0):
    """Assign per-prior targets from matched row entities (reference
    ``detection/target_assign_op.cc``): x [M, K] entity attributes,
    match_indices [N] (−1 = unmatched) → (out [N, K], weight [N])."""
    mi = match_indices.astype(jnp.int32)
    safe = jnp.maximum(mi, 0)
    out = x[safe]
    matched = (mi >= 0)[:, None]
    out = jnp.where(matched, out, mismatch_value)
    return out, matched[:, 0].astype(x.dtype)


def distribute_fpn_proposals(rois, min_level: int, max_level: int,
                             refer_level: int, refer_scale: float):
    """Route RoIs to FPN levels (reference
    ``detection/distribute_fpn_proposals_op.cc``): level =
    floor(refer_level + log2(sqrt(area)/refer_scale)) clipped to
    [min, max]. Fixed-shape: returns (level [R] int32, order [R]) —
    consumers gather per-level with a mask instead of splitting into
    LoD sublists."""
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
    lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-12))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    order = jnp.argsort(lvl, stable=True).astype(jnp.int32)
    return lvl, order


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n: int):
    """Merge per-level RoIs back by score (reference
    ``detection/collect_fpn_proposals_op.cc``): concat levels, top-k by
    score. Returns (rois [post_nms_top_n, 4], scores)."""
    rois = jnp.concatenate(multi_rois, axis=0)
    scores = jnp.concatenate(multi_scores, axis=0)
    k = min(post_nms_top_n, scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, k)
    return rois[idx], top_s
