"""paddle_tpu.vision — models, datasets, transforms.

Reference: ``python/paddle/vision`` (models: lenet/vgg/resnet/mobilenet,
datasets: MNIST/CIFAR/..., transforms).
"""

from paddle_tpu.vision import models, ops, transforms
from paddle_tpu.vision.datasets import (
    Cifar10, Cifar100, DatasetFolder, FashionMNIST, Flowers, ImageFolder,
    MNIST, RandomImageDataset, VOC2012,
)
