"""paddle_tpu.vision — models, datasets, transforms.

Reference: ``python/paddle/vision`` (models: lenet/vgg/resnet/mobilenet,
datasets: MNIST/CIFAR/..., transforms).
"""

from paddle_tpu.vision import models, transforms
from paddle_tpu.vision.datasets import MNIST, RandomImageDataset
