"""VGG (reference ``python/paddle/vision/models/vgg.py``)."""

from __future__ import annotations

from paddle_tpu.core.module import Module
from paddle_tpu.nn.activation import ReLU
from paddle_tpu.nn.common import Dropout, Flatten, Linear, Sequential
from paddle_tpu.nn.conv import Conv2D, MaxPool2D

__all__ = ["VGG", "vgg11", "vgg16"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
}


class VGG(Module):
    def __init__(self, cfg: str = "D", num_classes: int = 1000,
                 dropout: float = 0.5):
        layers = []
        in_c = 3
        for v in _CFGS[cfg]:
            if v == "M":
                layers.append(MaxPool2D(2, 2))
            else:
                layers.append(Conv2D(in_c, v, 3, padding=1))
                layers.append(ReLU())
                in_c = v
        self.features = Sequential(*layers)
        self.classifier = Sequential(
            Flatten(),
            Linear(512 * 7 * 7, 4096), ReLU(), Dropout(dropout),
            Linear(4096, 4096), ReLU(), Dropout(dropout),
            Linear(4096, num_classes),
        )

    def __call__(self, x, training: bool = False):
        return self.classifier(self.features(x, training=training),
                               training=training)


def vgg11(**kw):
    return VGG("A", **kw)


def vgg16(**kw):
    return VGG("D", **kw)
