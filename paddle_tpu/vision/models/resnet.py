"""ResNet family (reference ``python/paddle/vision/models/resnet.py``).

Conv+BN+ReLU: XLA fuses BN (inference) into the conv epilogue; training-
mode batch stats ride the state tape. Data format NCHW for reference API
parity (XLA relayouts internally for the TPU convolution).
"""

from __future__ import annotations

from paddle_tpu.core import rng
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.common import Linear
from paddle_tpu.nn.conv import AdaptiveAvgPool2D, Conv2D, MaxPool2D
from paddle_tpu.nn.norm import BatchNorm2D

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101"]


class BasicBlock(Module):
    expansion = 1

    def __init__(self, in_c: int, out_c: int, stride: int = 1,
                 downsample=None, key=None):
        k1, k2 = rng.split_key(key)
        self.conv1 = Conv2D(in_c, out_c, 3, stride=stride, padding=1,
                            bias=False, key=k1)
        self.bn1 = BatchNorm2D(out_c)
        self.conv2 = Conv2D(out_c, out_c, 3, padding=1, bias=False, key=k2)
        self.bn2 = BatchNorm2D(out_c)
        self.downsample = downsample

    def __call__(self, x, training: bool = False):
        identity = x
        out = F.relu(self.bn1(self.conv1(x), training=training))
        out = self.bn2(self.conv2(out), training=training)
        if self.downsample is not None:
            identity = self.downsample(x, training=training)
        return F.relu(out + identity)


class BottleneckBlock(Module):
    expansion = 4

    def __init__(self, in_c: int, out_c: int, stride: int = 1,
                 downsample=None, key=None):
        keys = rng.split_key(key, 3)
        self.conv1 = Conv2D(in_c, out_c, 1, bias=False, key=keys[0])
        self.bn1 = BatchNorm2D(out_c)
        self.conv2 = Conv2D(out_c, out_c, 3, stride=stride, padding=1,
                            bias=False, key=keys[1])
        self.bn2 = BatchNorm2D(out_c)
        self.conv3 = Conv2D(out_c, out_c * 4, 1, bias=False, key=keys[2])
        self.bn3 = BatchNorm2D(out_c * 4)
        self.downsample = downsample

    def __call__(self, x, training: bool = False):
        identity = x
        out = F.relu(self.bn1(self.conv1(x), training=training))
        out = F.relu(self.bn2(self.conv2(out), training=training))
        out = self.bn3(self.conv3(out), training=training)
        if self.downsample is not None:
            identity = self.downsample(x, training=training)
        return F.relu(out + identity)


class _Downsample(Module):
    def __init__(self, in_c: int, out_c: int, stride: int, key=None):
        self.conv = Conv2D(in_c, out_c, 1, stride=stride, bias=False, key=key)
        self.bn = BatchNorm2D(out_c)

    def __call__(self, x, training: bool = False):
        return self.bn(self.conv(x), training=training)


class ResNet(Module):
    def __init__(self, block, depths, num_classes: int = 1000,
                 in_channels: int = 3, key=None):
        self.conv1 = Conv2D(in_channels, 64, 7, stride=2, padding=3,
                            bias=False)
        self.bn1 = BatchNorm2D(64)
        self.maxpool = MaxPool2D(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, 64, depths[0], 1)
        self.layer2 = self._make_layer(block, 64 * block.expansion, 128,
                                       depths[1], 2)
        self.layer3 = self._make_layer(block, 128 * block.expansion, 256,
                                       depths[2], 2)
        self.layer4 = self._make_layer(block, 256 * block.expansion, 512,
                                       depths[3], 2)
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc = Linear(512 * block.expansion, num_classes)

    @staticmethod
    def _make_layer(block, in_c, out_c, depth, stride):
        layers = []
        downsample = None
        if stride != 1 or in_c != out_c * block.expansion:
            downsample = _Downsample(in_c, out_c * block.expansion, stride)
        layers.append(block(in_c, out_c, stride, downsample))
        for _ in range(1, depth):
            layers.append(block(out_c * block.expansion, out_c))
        return tuple(layers)

    def __call__(self, x, training: bool = False):
        x = F.relu(self.bn1(self.conv1(x), training=training))
        x = self.maxpool(x)
        for stage in (self.layer1, self.layer2, self.layer3, self.layer4):
            for blk in stage:
                x = blk(x, training=training)
        x = self.avgpool(x)
        return self.fc(x.reshape(x.shape[0], -1))


def resnet18(num_classes: int = 1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes: int = 1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)
