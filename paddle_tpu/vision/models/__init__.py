from paddle_tpu.vision.models.lenet import LeNet
from paddle_tpu.vision.models.resnet import (
    ResNet, resnet18, resnet34, resnet50, resnet101,
)
from paddle_tpu.vision.models.vgg import VGG, vgg11, vgg16
from paddle_tpu.vision.models.mobilenet import MobileNetV1, MobileNetV2
from paddle_tpu.vision.models.vit import ViT, vit_b_16, vit_l_16
from paddle_tpu.vision.models.ppyoloe import (
    PPYOLOE, PPYOLOEConfig, ppyoloe_s, ppyoloe_tiny,
)
