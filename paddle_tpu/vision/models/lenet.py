"""LeNet (reference ``python/paddle/vision/models/lenet.py``)."""

from __future__ import annotations

from paddle_tpu.core.module import Module
from paddle_tpu.nn.activation import ReLU
from paddle_tpu.nn.common import Flatten, Linear, Sequential
from paddle_tpu.nn.conv import Conv2D, MaxPool2D

__all__ = ["LeNet"]


class LeNet(Module):
    def __init__(self, num_classes: int = 10, key=None):
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2),
        )
        self.fc = Sequential(
            Flatten(),
            Linear(400, 120), ReLU(),
            Linear(120, 84), ReLU(),
            Linear(84, num_classes),
        )

    def __call__(self, x, training: bool = False):
        return self.fc(self.features(x, training=training))
