"""Vision Transformer (BASELINE.json config "ViT-L").

Patch embedding as a strided conv feeding scan-stacked transformer
blocks — the same ScannedBlocks machinery as the LLMs, so ViT trains
under any fleet strategy (dp/fsdp/tp) unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core import rng
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.common import Dropout, Linear
from paddle_tpu.nn.conv import Conv2D
from paddle_tpu.nn.initializer import Normal, TruncatedNormal
from paddle_tpu.nn.norm import LayerNorm
from paddle_tpu.nn.scan import ScannedBlocks

__all__ = ["ViT", "vit_b_16", "vit_l_16"]


class ViTBlock(Module):
    def __init__(self, dim: int, heads: int, mlp_dim: int,
                 dropout: float = 0.0, key=None):
        keys = rng.split_key(key, 4)
        self.ln1 = LayerNorm(dim)
        self.wqkv = Linear(dim, 3 * dim, key=keys[0], pspec=P("fsdp", "tp"))
        self.wo = Linear(dim, dim, key=keys[1], pspec=P("tp", "fsdp"))
        self.ln2 = LayerNorm(dim)
        self.fc1 = Linear(dim, mlp_dim, key=keys[2], pspec=P("fsdp", "tp"))
        self.fc2 = Linear(mlp_dim, dim, key=keys[3], pspec=P("tp", "fsdp"))
        self.drop = Dropout(dropout)
        self.heads = heads
        self.head_dim = dim // heads

    def __call__(self, x, training: bool = False):
        B, T, E = x.shape
        h = self.ln1(x)
        qkv = self.wqkv(h).reshape(B, T, 3, self.heads, self.head_dim)
        a = F.scaled_dot_product_attention(qkv[:, :, 0], qkv[:, :, 1],
                                           qkv[:, :, 2], causal=False)
        x = x + self.drop(self.wo(a.reshape(B, T, E)), training=training)
        h = self.ln2(x)
        h = self.fc2(F.gelu(self.fc1(h)))
        return x + self.drop(h, training=training)


class ViT(Module):
    def __init__(self, image_size: int = 224, patch_size: int = 16,
                 dim: int = 768, depth: int = 12, heads: int = 12,
                 mlp_dim: int = 3072, num_classes: int = 1000,
                 dropout: float = 0.0, remat: bool = False, key=None):
        n_patches = (image_size // patch_size) ** 2
        self.patch_embed = Conv2D(3, dim, patch_size, stride=patch_size)
        self.cls_token = TruncatedNormal(std=0.02)(
            rng.next_key(), (1, 1, dim))
        self.pos_embed = TruncatedNormal(std=0.02)(
            rng.next_key(), (1, n_patches + 1, dim))
        self.blocks = ScannedBlocks(
            lambda i: ViTBlock(dim, heads, mlp_dim, dropout), depth,
            remat=remat)
        self.ln = LayerNorm(dim)
        self.head = Linear(dim, num_classes,
                           weight_init=Normal(0.0, 0.01))
        self.dropout = Dropout(dropout)

    def __call__(self, x, training: bool = False):
        B = x.shape[0]
        p = self.patch_embed(x)                       # [B, dim, H', W']
        p = p.reshape(B, p.shape[1], -1).transpose(0, 2, 1)
        cls = jnp.broadcast_to(self.cls_token, (B, 1, p.shape[-1]))
        x = jnp.concatenate([cls, p], axis=1) + self.pos_embed
        x = self.dropout(x, training=training)
        x = self.blocks(x, training=training)
        return self.head(self.ln(x[:, 0]))


def vit_b_16(**kw):
    return ViT(dim=768, depth=12, heads=12, mlp_dim=3072, **kw)


def vit_l_16(**kw):
    return ViT(dim=1024, depth=24, heads=16, mlp_dim=4096, **kw)
