"""PP-YOLOE-class anchor-free detector (BASELINE.json names PP-YOLOE).

The reference repo predates PP-YOLOE but ships the op substrate this
model family is built from (``paddle/fluid/operators/detection/``:
yolo_box, NMS, anchors, IoU); the detector here is the TPU-native
composition of that op family into the modern anchor-free pipeline:

- **CSPResNet backbone** with RepVGG-style 3×3+1×1 dual-branch blocks,
- **CSP-PAN neck** (top-down + bottom-up, SPP in the deepest stage),
- **ET-head**: per-level classification (varifocal loss) and a
  distribution-focal regression branch (l, t, r, b over ``reg_max+1``
  bins, decoded by expectation),
- **Task-aligned assignment** (TAL) — implemented fully statically:
  per-gt top-k candidate selection and conflict resolution are masked
  tensor ops, no dynamic shapes anywhere,
- eval-time decode → ``vision.ops.multiclass_nms`` (padded/masked, the
  reference ``detection/multiclass_nms_op.cc`` semantics).

Everything jits; ground truth arrives padded ([B, G, 4] boxes and
[B, G] labels with -1 padding), which is also the collate format of
``vision.datasets`` detection pipelines.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from paddle_tpu.core import rng
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.conv import Conv2D, MaxPool2D
from paddle_tpu.nn.norm import BatchNorm2D
from paddle_tpu.vision import ops as V

__all__ = ["PPYOLOEConfig", "PPYOLOE", "ppyoloe_tiny", "ppyoloe_s"]


@dataclass(frozen=True)
class PPYOLOEConfig:
    num_classes: int = 80
    # backbone: channels per stage and blocks per stage
    stage_channels: tuple = (64, 128, 256, 512)
    stage_blocks: tuple = (1, 2, 2, 1)
    stem_channels: int = 32
    # neck output channels per level (P3, P4, P5)
    neck_channels: tuple = (96, 192, 384)
    strides: tuple = (8, 16, 32)
    reg_max: int = 16
    # TAL
    tal_topk: int = 13
    tal_alpha: float = 1.0
    tal_beta: float = 6.0
    # loss weights (PP-YOLOE defaults)
    cls_weight: float = 1.0
    iou_weight: float = 2.5
    dfl_weight: float = 0.5
    # eval
    score_threshold: float = 0.01
    nms_threshold: float = 0.6
    nms_top_k: int = 400
    keep_top_k: int = 100

    @classmethod
    def tiny(cls, num_classes: int = 8):
        return cls(num_classes=num_classes, stage_channels=(32, 48, 64, 96),
                   stage_blocks=(1, 1, 1, 1), stem_channels=16,
                   neck_channels=(32, 48, 64), reg_max=8, nms_top_k=100,
                   keep_top_k=20)


class ConvBNAct(Module):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1, act="swish",
                 key=None):
        self.conv = Conv2D(in_c, out_c, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups, bias=False,
                           key=key)
        self.bn = BatchNorm2D(out_c)
        self.act = act

    def __call__(self, x, training: bool = False):
        x = self.bn(self.conv(x), training=training)
        return F.swish(x) if self.act == "swish" else x


class RepVggBlock(Module):
    """Dual-branch 3×3 + 1×1 conv-BN (train form). The inference-time
    reparameterization to one 3×3 is a pure weight transform
    (``fuse()``), not a separate architecture."""

    def __init__(self, in_c, out_c, key=None):
        k1, k2 = rng.split_key(key)
        self.conv3 = ConvBNAct(in_c, out_c, 3, act="none", key=k1)
        self.conv1 = ConvBNAct(in_c, out_c, 1, act="none", key=k2)

    def __call__(self, x, training: bool = False):
        return F.swish(self.conv3(x, training=training)
                       + self.conv1(x, training=training))


class ESEAttn(Module):
    """Effective squeeze-excitation (one fc) used by the head stem."""

    def __init__(self, ch, key=None):
        k1, k2 = rng.split_key(key)
        self.fc = Conv2D(ch, ch, 1, key=k1)
        self.conv = ConvBNAct(ch, ch, 1, key=k2)

    def __call__(self, feat, avg_feat, training: bool = False):
        w = F.sigmoid(self.fc(avg_feat))
        return self.conv(feat * w, training=training)


class CSPResStage(Module):
    def __init__(self, in_c, out_c, n_blocks, stride, key=None):
        keys = rng.split_key(key, n_blocks + 4)
        mid = out_c // 2
        self.down = (ConvBNAct(in_c, in_c, 3, stride=stride, key=keys[0])
                     if stride > 1 else None)
        self.conv1 = ConvBNAct(in_c, mid, 1, key=keys[1])
        self.conv2 = ConvBNAct(in_c, mid, 1, key=keys[2])
        self.blocks = tuple(
            RepVggBlock(mid, mid, key=keys[3 + i]) for i in range(n_blocks))
        self.conv3 = ConvBNAct(mid * 2, out_c, 1, key=keys[-1])

    def __call__(self, x, training: bool = False):
        if self.down is not None:
            x = self.down(x, training=training)
        y1 = self.conv1(x, training=training)
        y2 = self.conv2(x, training=training)
        for b in self.blocks:
            y2 = b(y2, training=training)
        return self.conv3(jnp.concatenate([y1, y2], axis=1),
                          training=training)


class CSPResNet(Module):
    """Backbone; returns (C3, C4, C5) feature maps at strides 8/16/32."""

    def __init__(self, cfg: PPYOLOEConfig, key=None):
        keys = rng.split_key(key, 3 + len(cfg.stage_channels))
        sc = cfg.stem_channels
        self.stem1 = ConvBNAct(3, sc, 3, stride=2, key=keys[0])
        self.stem2 = ConvBNAct(sc, sc * 2, 3, stride=1, key=keys[1])
        chans = (sc * 2,) + cfg.stage_channels
        self.stages = tuple(
            CSPResStage(chans[i], chans[i + 1], cfg.stage_blocks[i],
                        stride=2, key=keys[2 + i])
            for i in range(len(cfg.stage_channels)))

    def __call__(self, x, training: bool = False):
        x = self.stem2(self.stem1(x, training=training), training=training)
        feats = []
        for st in self.stages:
            x = st(x, training=training)
            feats.append(x)
        return feats[-3], feats[-2], feats[-1]


class SPP(Module):
    def __init__(self, in_c, out_c, key=None):
        self.pools = tuple(MaxPool2D(k, 1, k // 2) for k in (5, 9, 13))
        self.conv = ConvBNAct(in_c * 4, out_c, 1, key=key)

    def __call__(self, x, training: bool = False):
        parts = [x] + [p(x) for p in self.pools]
        return self.conv(jnp.concatenate(parts, axis=1), training=training)


class CSPStage(Module):
    def __init__(self, in_c, out_c, n=1, spp: bool = False, key=None):
        keys = rng.split_key(key, n + 4)
        mid = out_c // 2
        self.conv1 = ConvBNAct(in_c, mid, 1, key=keys[0])
        self.conv2 = ConvBNAct(in_c, mid, 1, key=keys[1])
        blocks = []
        for i in range(n):
            blocks.append(RepVggBlock(mid, mid, key=keys[2 + i]))
        self.blocks = tuple(blocks)
        self.spp = SPP(mid, mid, key=keys[-2]) if spp else None
        self.conv3 = ConvBNAct(mid * 2, out_c, 1, key=keys[-1])

    def __call__(self, x, training: bool = False):
        y1 = self.conv1(x, training=training)
        y2 = self.conv2(x, training=training)
        for b in self.blocks:
            y2 = b(y2, training=training)
        if self.spp is not None:
            y2 = self.spp(y2, training=training)
        return self.conv3(jnp.concatenate([y1, y2], axis=1),
                          training=training)


def _upsample2(x):
    n, c, h, w = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :, None],
                            (n, c, h, 2, w, 2)).reshape(n, c, h * 2, w * 2)


class CSPPAN(Module):
    """Top-down FPN + bottom-up PAN, CSP blocks at every fusion."""

    def __init__(self, in_chs, out_chs, key=None):
        keys = rng.split_key(key, 12)
        c3, c4, c5 = in_chs
        o3, o4, o5 = out_chs
        self.reduce5 = CSPStage(c5, o5, spp=True, key=keys[0])
        self.lat4 = ConvBNAct(o5, o4, 1, key=keys[1])
        self.td4 = CSPStage(c4 + o4, o4, key=keys[2])
        self.lat3 = ConvBNAct(o4, o3, 1, key=keys[3])
        self.td3 = CSPStage(c3 + o3, o3, key=keys[4])
        self.down3 = ConvBNAct(o3, o3, 3, stride=2, key=keys[5])
        self.bu4 = CSPStage(o3 + o4, o4, key=keys[6])
        self.down4 = ConvBNAct(o4, o4, 3, stride=2, key=keys[7])
        self.bu5 = CSPStage(o4 + o5, o5, key=keys[8])

    def __call__(self, feats, training: bool = False):
        c3, c4, c5 = feats
        p5 = self.reduce5(c5, training=training)
        up4 = _upsample2(self.lat4(p5, training=training))
        p4 = self.td4(jnp.concatenate([c4, up4], axis=1), training=training)
        up3 = _upsample2(self.lat3(p4, training=training))
        p3 = self.td3(jnp.concatenate([c3, up3], axis=1), training=training)
        n4 = self.bu4(jnp.concatenate(
            [self.down3(p3, training=training), p4], axis=1),
            training=training)
        n5 = self.bu5(jnp.concatenate(
            [self.down4(n4, training=training), p5], axis=1),
            training=training)
        return p3, n4, n5


class PPYOLOEHead(Module):
    def __init__(self, cfg: PPYOLOEConfig, key=None):
        nl = len(cfg.neck_channels)
        keys = rng.split_key(key, 4 * nl)
        self.cfg = cfg
        self.stem_cls = tuple(ESEAttn(c, key=keys[i])
                              for i, c in enumerate(cfg.neck_channels))
        self.stem_reg = tuple(ESEAttn(c, key=keys[nl + i])
                              for i, c in enumerate(cfg.neck_channels))
        # bias init: cls prior ~1% positive (focal-style); reg biased to
        # the first distance bin so initial boxes start ~1 stride wide
        self.pred_cls = tuple(
            Conv2D(c, cfg.num_classes, 3, padding=1, key=keys[2 * nl + i])
            for i, c in enumerate(cfg.neck_channels))
        self.pred_reg = tuple(
            Conv2D(c, 4 * (cfg.reg_max + 1), 3, padding=1,
                   key=keys[3 * nl + i])
            for i, c in enumerate(cfg.neck_channels))
        prior = -math.log((1 - 0.01) / 0.01)
        self.pred_cls = tuple(
            m.replace(bias=m.bias + prior) for m in self.pred_cls)
        reg_bias = jnp.tile(
            jnp.asarray([4.0] + [0.0] * cfg.reg_max, jnp.float32), 4)
        self.pred_reg = tuple(
            m.replace(bias=m.bias + reg_bias) for m in self.pred_reg)

    def __call__(self, feats, training: bool = False):
        """Returns (cls_logits [B, L, NC], reg_dist [B, L, 4, reg_max+1],
        anchor points [L, 2], strides [L, 1])."""
        cfg = self.cfg
        cls_list, reg_list, shapes = [], [], []
        for i, f in enumerate(feats):
            B, C, H, W = f.shape
            avg = jnp.mean(f, axis=(2, 3), keepdims=True)
            cl = self.pred_cls[i](
                self.stem_cls[i](f, avg, training=training) + f)
            rg = self.pred_reg[i](
                self.stem_reg[i](f, avg, training=training))
            cls_list.append(cl.reshape(B, cfg.num_classes, H * W)
                            .transpose(0, 2, 1))
            reg_list.append(
                rg.reshape(B, 4, cfg.reg_max + 1, H * W)
                .transpose(0, 3, 1, 2))
            shapes.append((H, W))
        points, strides = V.generate_anchor_points(shapes, cfg.strides)
        return (jnp.concatenate(cls_list, axis=1),
                jnp.concatenate(reg_list, axis=1), points, strides)


def _dfl_expect(reg_dist):
    """[..., 4, reg_max+1] logits → expected (l, t, r, b) in stride
    units (distribution-focal decode)."""
    n_bins = reg_dist.shape[-1]
    proj = jnp.arange(n_bins, dtype=jnp.float32)
    return jnp.sum(jax.nn.softmax(reg_dist, axis=-1) * proj, axis=-1)


def _tal_assign(pred_scores, pred_bboxes, points, gt_boxes, gt_labels,
                *, topk: int, alpha: float, beta: float, num_classes: int):
    """Task-aligned assignment for ONE image, fully static.

    pred_scores [L, NC] (sigmoid), pred_bboxes [L, 4] (pixels),
    points [L, 2], gt_boxes [G, 4], gt_labels [G] int (-1 = pad).
    Returns (target_labels [L] int (num_classes = bg), target_boxes
    [L, 4], target_scores [L, NC] soft).
    """
    L = points.shape[0]
    G = gt_boxes.shape[0]
    valid_gt = gt_labels >= 0                                   # [G]

    iou = V.box_iou_xyxy(gt_boxes, pred_bboxes)                 # [G, L]
    safe_labels = jnp.clip(gt_labels, 0, num_classes - 1)
    cls_score = pred_scores[:, safe_labels].T                   # [G, L]
    metric = (cls_score ** alpha) * (iou ** beta)

    # candidates must have their center inside the gt box
    inside = ((points[None, :, 0] >= gt_boxes[:, None, 0])
              & (points[None, :, 0] <= gt_boxes[:, None, 2])
              & (points[None, :, 1] >= gt_boxes[:, None, 1])
              & (points[None, :, 1] <= gt_boxes[:, None, 3]))   # [G, L]
    metric = jnp.where(inside & valid_gt[:, None], metric, 0.0)

    # per-gt top-k candidate mask (static k)
    k = min(topk, L)
    kth = -jax.lax.top_k(metric, k)[0][:, -1:]                  # [G, 1]
    cand = (metric >= jnp.maximum(-kth, 1e-12)) & (metric > 0)  # [G, L]

    # conflicts: an anchor claimed by several gts goes to the max-IoU one
    iou_cand = jnp.where(cand, iou, -1.0)
    owner = jnp.argmax(iou_cand, axis=0)                        # [L]
    assigned = jnp.max(iou_cand, axis=0) > 0                    # [L]

    t_labels = jnp.where(assigned, gt_labels[owner], num_classes)
    t_boxes = gt_boxes[owner]

    # normalized soft targets: metric scaled per gt to its max IoU
    m_max = jnp.max(metric, axis=1, keepdims=True)              # [G, 1]
    i_max = jnp.max(jnp.where(cand, iou, 0.0), axis=1, keepdims=True)
    norm_metric = metric / jnp.maximum(m_max, 1e-9) * i_max     # [G, L]
    t_score_val = jnp.where(assigned, norm_metric[owner, jnp.arange(L)], 0.0)
    t_scores = jax.nn.one_hot(t_labels, num_classes) \
        * t_score_val[:, None]                                  # [L, NC]
    return t_labels, t_boxes, t_scores


def _varifocal_loss(logits, target_scores, t_labels, num_classes,
                    alpha=0.75, gamma=2.0):
    """VFL: positives weighted by their (soft) target score, negatives by
    alpha·p^gamma (PP-YOLOE classification loss)."""
    p = jax.nn.sigmoid(logits)
    pos = (t_labels < num_classes)[:, None] * (target_scores > 0)
    weight = jnp.where(pos, target_scores, alpha * p ** gamma)
    bce = jnp.maximum(logits, 0) - logits * target_scores \
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(weight * bce)


def _giou(b1, b2):
    iou = V.box_iou_xyxy(b1[:, None], b2[:, None])[:, 0, 0]
    x1 = jnp.minimum(b1[:, 0], b2[:, 0])
    y1 = jnp.minimum(b1[:, 1], b2[:, 1])
    x2 = jnp.maximum(b1[:, 2], b2[:, 2])
    y2 = jnp.maximum(b1[:, 3], b2[:, 3])
    hull = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    a1 = jnp.maximum(b1[:, 2] - b1[:, 0], 0) \
        * jnp.maximum(b1[:, 3] - b1[:, 1], 0)
    a2 = jnp.maximum(b2[:, 2] - b2[:, 0], 0) \
        * jnp.maximum(b2[:, 3] - b2[:, 1], 0)
    inter = iou * jnp.maximum(a1 + a2, 1e-9) / jnp.maximum(1 + iou, 1e-9)
    union = a1 + a2 - inter
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


class PPYOLOE(Module):
    """Full detector. ``loss(images, gt_boxes, gt_labels)`` for training
    (padded gt, -1 labels); ``predict(images, img_size)`` for eval
    (decoded + class-aware NMS, fixed-shape [B, keep_top_k, 6])."""

    def __init__(self, cfg: PPYOLOEConfig, key=None):
        keys = rng.split_key(key, 3)
        self.config = cfg
        self.backbone = CSPResNet(cfg, key=keys[0])
        bb = (cfg.stage_channels[-3], cfg.stage_channels[-2],
              cfg.stage_channels[-1])
        self.neck = CSPPAN(bb, cfg.neck_channels, key=keys[1])
        self.head = PPYOLOEHead(cfg, key=keys[2])

    def __call__(self, images, training: bool = False):
        feats = self.neck(self.backbone(images, training=training),
                          training=training)
        return self.head(feats, training=training)

    def _decode(self, reg_dist, points, strides):
        dist = _dfl_expect(reg_dist) * strides[None]        # [B, L, 4] px
        return V.distance2bbox(points[None], dist)

    def loss(self, images, gt_boxes, gt_labels, training: bool = True):
        """Scoped mixed precision: only the network forward
        (backbone/neck/head convs — the FLOPs) rides an ambient
        ``amp.auto_cast``; decode, TAL assignment (top-k/IoU) and the
        VFL/DFL/GIoU losses below are pinned fp32 via ``amp.suspend``.
        Whole-model autocast measured 15× SLOWER than fp32 on a v5e
        (BASELINE.md r3): per-op cast boundaries inside the assignment
        break XLA fusion; the head outputs are small, so casting once
        here is free."""
        from paddle_tpu import amp as _amp

        cls_logits, reg_dist, points, strides = self(
            images, training=training)
        with _amp.suspend():
            cls_logits = cls_logits.astype(jnp.float32)
            reg_dist = reg_dist.astype(jnp.float32)
            return self._loss_tail(cls_logits, reg_dist, points, strides,
                                   gt_boxes, gt_labels)

    def _loss_tail(self, cls_logits, reg_dist, points, strides,
                   gt_boxes, gt_labels):
        cfg = self.config
        pred_boxes = self._decode(reg_dist, points, strides)
        pred_scores = jax.nn.sigmoid(cls_logits)

        assign = jax.vmap(lambda s, b, gb, gl: _tal_assign(
            s, b, points, gb, gl, topk=cfg.tal_topk, alpha=cfg.tal_alpha,
            beta=cfg.tal_beta, num_classes=cfg.num_classes))
        t_labels, t_boxes, t_scores = assign(
            jax.lax.stop_gradient(pred_scores),
            jax.lax.stop_gradient(pred_boxes), gt_boxes, gt_labels)

        B, L = t_labels.shape
        pos = t_labels < cfg.num_classes                      # [B, L]
        score_sum = jnp.maximum(jnp.sum(t_scores), 1.0)

        cls_loss = jax.vmap(lambda lg, ts, tl: _varifocal_loss(
            lg, ts, tl, cfg.num_classes))(cls_logits, t_scores,
                                          t_labels).sum() / score_sum

        # box losses on positives, weighted by the assigned soft score
    # (flatten batch; masked)
        w = jnp.where(pos, jnp.sum(t_scores, axis=-1), 0.0).reshape(-1)
        pb = pred_boxes.reshape(-1, 4)
        tb = t_boxes.reshape(-1, 4)
        giou = _giou(pb, tb)
        iou_loss = jnp.sum(w * (1.0 - giou)) / score_sum

        # DFL: distribution over bins vs the (clipped) true distance
        tdist = V.bbox2distance(
            jnp.broadcast_to(points[None], (B, L, 2)).reshape(-1, 2), tb,
            max_dist=None) / jnp.broadcast_to(
                strides[None], (B, L, 1)).reshape(-1, 1)
        tdist = jnp.clip(tdist, 0.0, cfg.reg_max - 0.01)      # [BL, 4]
        li = jnp.floor(tdist)
        wr = tdist - li
        logp = jax.nn.log_softmax(reg_dist.reshape(-1, 4, cfg.reg_max + 1),
                                  axis=-1)
        gl = jnp.take_along_axis(logp, li.astype(jnp.int32)[..., None],
                                 axis=-1)[..., 0]
        gr = jnp.take_along_axis(logp, (li + 1).astype(jnp.int32)[..., None],
                                 axis=-1)[..., 0]
        dfl = -(gl * (1 - wr) + gr * wr).mean(axis=-1)        # [BL]
        dfl_loss = jnp.sum(w * dfl) / score_sum

        total = (cfg.cls_weight * cls_loss + cfg.iou_weight * iou_loss
                 + cfg.dfl_weight * dfl_loss)
        return total

    def predict(self, images, img_size=None, training: bool = False):
        """→ (out [B, keep_top_k, 6] rows (label, score, x1, y1, x2, y2),
        num_valid [B])."""
        cfg = self.config
        cls_logits, reg_dist, points, strides = self(
            images, training=training)
        boxes = self._decode(reg_dist, points, strides)        # [B, L, 4]
        if img_size is not None:
            boxes = V.box_clip(boxes, img_size.astype(jnp.float32))
        scores = jax.nn.sigmoid(cls_logits).transpose(0, 2, 1)  # [B, NC, L]
        nms = jax.vmap(lambda b, s: V.multiclass_nms(
            b, s, cfg.score_threshold, cfg.nms_top_k, cfg.keep_top_k,
            cfg.nms_threshold, normalized=False))
        return nms(boxes, scores)


def ppyoloe_tiny(num_classes: int = 8, **kw):
    return PPYOLOE(PPYOLOEConfig.tiny(num_classes=num_classes), **kw)


def ppyoloe_s(num_classes: int = 80, **kw):
    return PPYOLOE(PPYOLOEConfig(num_classes=num_classes), **kw)
