"""MobileNetV1/V2 (reference ``python/paddle/vision/models/mobilenetv{1,2}.py``).
Depthwise convs = grouped conv (groups == channels), which XLA lowers to
TPU-friendly contractions."""

from __future__ import annotations

from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.common import Dropout, Linear
from paddle_tpu.nn.conv import AdaptiveAvgPool2D, Conv2D
from paddle_tpu.nn.norm import BatchNorm2D

__all__ = ["MobileNetV1", "MobileNetV2"]


class ConvBNReLU(Module):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1):
        pad = (kernel - 1) // 2
        self.conv = Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                           groups=groups, bias=False)
        self.bn = BatchNorm2D(out_c)

    def __call__(self, x, training: bool = False):
        return F.relu6(self.bn(self.conv(x), training=training))


class InvertedResidual(Module):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(in_c, hidden, kernel=1))
        layers.append(ConvBNReLU(hidden, hidden, stride=stride,
                                 groups=hidden))
        self.layers = tuple(layers)
        self.project = Conv2D(hidden, out_c, 1, bias=False)
        self.project_bn = BatchNorm2D(out_c)

    def __call__(self, x, training: bool = False):
        out = x
        for layer in self.layers:
            out = layer(out, training=training)
        out = self.project_bn(self.project(out), training=training)
        return x + out if self.use_res else out


class MobileNetV2(Module):
    def __init__(self, num_classes: int = 1000, width_mult: float = 1.0,
                 dropout: float = 0.2):
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = int(32 * width_mult)
        self.stem = ConvBNReLU(3, in_c, stride=2)
        blocks = []
        for t, c, n, s in cfg:
            out_c = int(c * width_mult)
            for i in range(n):
                blocks.append(InvertedResidual(in_c, out_c,
                                               s if i == 0 else 1, t))
                in_c = out_c
        self.blocks = tuple(blocks)
        last = int(1280 * max(1.0, width_mult))
        self.head_conv = ConvBNReLU(in_c, last, kernel=1)
        self.pool = AdaptiveAvgPool2D(1)
        self.dropout = Dropout(dropout)
        self.fc = Linear(last, num_classes)

    def __call__(self, x, training: bool = False):
        x = self.stem(x, training=training)
        for b in self.blocks:
            x = b(x, training=training)
        x = self.head_conv(x, training=training)
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.fc(self.dropout(x, training=training))


class DepthwiseSeparable(Module):
    """Depthwise 3x3 + pointwise 1x1 (reference mobilenetv1.py block)."""

    def __init__(self, in_c, out_c, stride):
        self.dw = ConvBNReLU(in_c, in_c, kernel=3, stride=stride,
                             groups=in_c)
        self.pw = ConvBNReLU(in_c, out_c, kernel=1)

    def __call__(self, x, training: bool = False):
        return self.pw(self.dw(x, training=training), training=training)


class MobileNetV1(Module):
    """MobileNetV1 (reference ``python/paddle/vision/models/mobilenetv1.py``)."""

    def __init__(self, num_classes: int = 1000, scale: float = 1.0):
        def c(ch):
            return int(ch * scale)

        self.stem = ConvBNReLU(3, c(32), stride=2)
        cfg = [
            # in, out, stride
            (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2),
            (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
            (512, 512, 1), (512, 1024, 2), (1024, 1024, 1),
        ]
        self.blocks = tuple(DepthwiseSeparable(c(i), c(o), s)
                            for i, o, s in cfg)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c(1024), num_classes)

    def __call__(self, x, training: bool = False):
        x = self.stem(x, training=training)
        for b in self.blocks:
            x = b(x, training=training)
        x = self.pool(x).reshape(x.shape[0], -1)
        return self.fc(x)
