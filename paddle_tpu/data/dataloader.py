"""DataLoader with a real parallel worker pool.

Reference: ``python/paddle/fluid/reader.py:147`` (DataLoader facade),
multiprocess iter ``fluid/dataloader/dataloader_iter.py:469``
(_DataLoaderIterMultiProcess: N workers + ordered reassembly by batch
index). The TPU host pipeline defaults to *thread* workers — numpy
collation and IO release the GIL, and forking a process that holds a
libtpu client is unsafe — with ``worker_mode="process"`` available for
pure-Python CPU-bound datasets. Both modes preserve batch order (the
reference's _order_ sending) and bound in-flight batches by the prefetch
depth. An optional device-prefetch stage overlaps ``device_put`` with
compute — the role the reference's pinned-memory + async memcpy path
plays on CUDA.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable

import numpy as np

from paddle_tpu.core.flags import flag
from paddle_tpu.data.dataset import Dataset, IterableDataset
from paddle_tpu.data.sampler import BatchSampler

__all__ = ["DataLoader", "default_collate", "ragged_collate"]

_STOP = object()

# Fork-pool worker state: the loader is stashed here by the Pool
# initializer (fork-inherited, never pickled); tasks then reference it by
# this global instead of shipping a bound method — which would pickle the
# DataLoader/dataset/collate_fn on every task.
_proc_loader = None


def _proc_worker_init(loader):
    global _proc_loader
    _proc_loader = loader


def _proc_worker_load(indices):
    return _proc_loader._load_batch(indices)


def default_collate(samples):
    """Stack a list of samples into a batch (numpy), matching the
    reference's default_collate_fn semantics (nested tuples/dicts ok)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


def ragged_collate(pad_value=0, bucket: int = 64, max_len: int | None = None):
    """Collate for variable-length samples (the LoD feed of the
    reference's sequence workloads, e.g. Imdb/Conll05st token ids).

    Returns a collate_fn: every field whose elements are arrays of rank
    ≥ 1 is treated as a sequence field — padded along dim 0 to the batch
    max rounded up to a multiple of ``bucket`` (bounds the number of
    distinct shapes XLA ever compiles, and keeps the batch structure
    identical whether or not a particular batch happens to have equal
    lengths) and replaced by a ``(padded [B, T, ...], lengths [B])``
    pair — the static (dense, lengths) encoding every op in
    ``paddle_tpu.ops.sequence`` consumes. Scalar fields (labels) stack.
    ``max_len`` is a hard cap: longer sequences are truncated and the
    padded width never exceeds it. All vectorized numpy — no per-token
    Python loops.
    """

    def pad_field(arrs):
        lengths = np.asarray([a.shape[0] for a in arrs], np.int32)
        t = max(-(-int(lengths.max()) // bucket) * bucket, bucket)
        if max_len is not None:
            t = min(t, max_len)
        out = np.full((len(arrs), t) + arrs[0].shape[1:], pad_value,
                      arrs[0].dtype)
        for i, a in enumerate(arrs):                 # per-sample memcpy
            n = min(a.shape[0], t)
            out[i, :n] = a[:n]
        return out, np.minimum(lengths, t)

    def collate(samples):
        first = samples[0]
        if isinstance(first, dict):
            return {k: collate([s[k] for s in samples]) for k in first}
        if isinstance(first, (tuple, list)):
            return type(first)(collate([s[i] for s in samples])
                               for i in range(len(first)))
        arrs = [np.asarray(s) for s in samples]
        if arrs[0].ndim >= 1:
            return pad_field(arrs)
        return np.stack(arrs)

    return collate


class DataLoader:
    def __init__(self, dataset, *, batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Callable | None = None,
                 num_workers: int = 0, prefetch_factor: int | None = None,
                 batch_sampler: BatchSampler | None = None,
                 device_put: bool = False, worker_mode: str = "thread"):
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode={worker_mode!r}")
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate
        self.num_workers = int(num_workers)
        self.worker_mode = worker_mode
        self.prefetch = (prefetch_factor if prefetch_factor is not None
                         else flag("host_prefetch_buffer"))
        self.device_put = device_put
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset=dataset, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------
    def _batches(self):
        if self._iterable:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _load_batch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _pool_batches_threads(self):
        """N thread workers, ordered reassembly: batches are submitted in
        sampler order and yielded in submission order, with at most
        ``num_workers + prefetch`` in flight."""
        window = self.num_workers + max(self.prefetch, 1)
        with ThreadPoolExecutor(self.num_workers) as ex:
            pending: deque = deque()
            for indices in self.batch_sampler:
                pending.append(ex.submit(self._load_batch, indices))
                if len(pending) >= window:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()

    def _pool_batches_procs(self):
        """N process workers (reference dataloader_iter.py:469). Fork-based
        so the dataset needn't pickle: the loader is inherited by each
        worker at fork time via a Pool initializer global, and tasks carry
        only the index lists — nothing else crosses the process boundary.
        Only safe when no accelerator client is live in the parent — use
        for CPU-bound pure-Python datasets."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        with ctx.Pool(self.num_workers, initializer=_proc_worker_init,
                      initargs=(self,)) as pool:
            # imap preserves order and streams results as they finish
            yield from pool.imap(_proc_worker_load,
                                 iter(self.batch_sampler),
                                 chunksize=1)

    def _single_producer(self):
        """One background producer feeding a bounded queue (used for
        IterableDataset, whose iteration order is inherently serial)."""
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        err: list[BaseException] = []

        def producer():
            try:
                for batch in self._batches():
                    q.put(batch)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(_STOP)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _STOP:
                if err:
                    raise err[0]
                return
            yield item

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._maybe_device(self._batches())
        elif self._iterable:
            yield from self._maybe_device(self._single_producer())
        elif self.worker_mode == "process":
            yield from self._maybe_device(self._pool_batches_procs())
        else:
            yield from self._maybe_device(self._pool_batches_threads())

    def _maybe_device(self, it: Iterable):
        if not self.device_put:
            yield from it
            return
        # double-buffer: keep one batch in flight on the device
        import jax

        prev = None
        for batch in it:
            nxt = jax.tree_util.tree_map(jax.device_put, batch)
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev
