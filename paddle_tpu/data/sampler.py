"""Samplers (reference ``python/paddle/fluid/dataloader/batch_sampler.py``
and ``python/paddle/io`` DistributedBatchSampler)."""

from __future__ import annotations

import math

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __init__(self, data_source):
        self.n = len(data_source)

    def __iter__(self):
        return iter(range(self.n))

    def __len__(self):
        return self.n


class RandomSampler(Sampler):
    def __init__(self, data_source, seed: int | None = None):
        self.n = len(data_source)
        self.seed = seed
        self._epoch = 0

    def __iter__(self):
        seed = (self.seed if self.seed is not None else 0) + self._epoch
        self._epoch += 1
        return iter(np.random.RandomState(seed).permutation(self.n).tolist())

    def __len__(self):
        return self.n


class BatchSampler(Sampler):
    def __init__(self, sampler: Sampler | None = None, dataset=None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """Shard batches across data-parallel processes (reference
    DistributedBatchSampler). On TPU each *process* feeds its local chips;
    rank/world default to jax process info."""

    def __init__(self, dataset, batch_size: int, num_replicas: int | None = None,
                 rank: int | None = None, shuffle: bool = False,
                 drop_last: bool = False, seed: int = 0):
        import jax

        self.num_replicas = (num_replicas if num_replicas is not None
                             else jax.process_count())
        self.rank = rank if rank is not None else jax.process_index()
        self.dataset_len = len(dataset)
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self.num_samples = math.ceil(self.dataset_len / self.num_replicas)

    def set_epoch(self, epoch: int):
        self._epoch = epoch

    def __iter__(self):
        if self.shuffle:
            order = np.random.RandomState(self.seed + self._epoch).permutation(
                self.dataset_len).tolist()
            self._epoch += 1
        else:
            order = list(range(self.dataset_len))
        # pad to be evenly divisible, then take this rank's strided slice.
        # Tile (not slice-once): when dataset_len < num_replicas the pad
        # exceeds len(order) and a single `order[:pad]` would under-pad,
        # desynchronizing per-rank shard counts across hosts.
        total = self.num_samples * self.num_replicas
        while len(order) < total:
            order += order[: total - len(order)]
        local = order[self.rank::self.num_replicas]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)
