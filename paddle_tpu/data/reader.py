"""Reader decorators — the ``paddle.batch`` / ``paddle.reader`` surface.

Reference: ``python/paddle/batch.py:18`` (mini-batching decorator over a
sample generator) and the ``fluid/reader``-era composers (shuffle,
chain). Kept for API parity with generator-based input pipelines; new
code should prefer ``paddle_tpu.data.DataLoader``.
"""

from __future__ import annotations

import random as _random

__all__ = ["batch", "shuffle", "chain"]


def batch(reader, batch_size: int, drop_last: bool = False):
    """Wrap a sample-generator factory into a mini-batch generator
    factory (reference ``paddle.batch``)."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def shuffle(reader, buf_size: int, seed: int | None = None):
    """Buffered shuffle of a sample generator (reference
    ``fluid.io.shuffle``)."""

    if buf_size <= 0:
        raise ValueError(f"buf_size must be positive, got {buf_size}")

    def shuffled():
        rng = _random.Random(seed)
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    """Concatenate sample generators (reference ``fluid.io.chain``)."""

    def chained():
        for r in readers:
            yield from r()

    return chained
