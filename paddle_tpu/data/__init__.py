"""paddle_tpu.data — datasets, samplers, DataLoader.

Mirrors ``paddle.io`` (reference ``python/paddle/fluid/reader.py:147``
DataLoader, ``python/paddle/fluid/dataloader/``): map/iterable datasets,
batch samplers, and a prefetching loader. The TPU-native difference: the
loader's job is to keep the *host→device* pipe full (XLA owns the device),
so prefetch = background threads + ``jax.device_put`` double-buffering
instead of the reference's multiprocess workers + LoDTensor queues; a C++
packed-feed path (``paddle_tpu.native``) covers the hot case.
"""

from paddle_tpu.data.dataset import (
    ChainDataset, Dataset, IterableDataset, Subset, TensorDataset,
    random_split,
)
from paddle_tpu.data.sampler import (
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler,
)
from paddle_tpu.data.dataloader import DataLoader, default_collate, ragged_collate
from paddle_tpu.data.reader import batch, chain, shuffle
