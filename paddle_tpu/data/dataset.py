"""Dataset abstractions (reference ``python/paddle/fluid/dataloader/dataset.py``)."""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "Subset",
           "ChainDataset", "random_split"]


class Dataset:
    """Map-style dataset: implement __getitem__ and __len__."""

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError


class IterableDataset:
    """Stream-style dataset: implement __iter__."""

    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        tensors = [np.asarray(t) for t in tensors]
        n = len(tensors[0])
        if any(len(t) != n for t in tensors):
            raise ValueError("all tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        out = tuple(t[idx] for t in self.tensors)
        return out if len(out) > 1 else out[0]

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


def random_split(dataset: Dataset, lengths: Sequence[int], seed: int = 0):
    if sum(lengths) != len(dataset):
        raise ValueError("lengths must sum to dataset size")
    perm = np.random.RandomState(seed).permutation(len(dataset))
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start:start + n].tolist()))
        start += n
    return out
