"""Sequence / ragged operators — the LoD op family, TPU-native.

The reference handles variable-length data with LoD tensors and ~20
per-sequence CPU/CUDA loops under
``paddle/fluid/operators/sequence_ops/`` (``sequence_pool_op.cc``,
``sequence_conv_op.cc``, ``sequence_pad_op.cc``, …, plus
``operators/math/sequence_pooling.cu``). LoD — a host-side list of
offsets changing per batch — cannot exist under XLA's static shapes, so
the TPU representation is **(padded dense, lengths)** for batched data
and **(flat values, segment_ids)** for fully ragged data; every op here
is a masked static-shape computation over one of those two encodings.

Segment reductions are the ``SelectedRows``/sequence-pooling analogue
and vectorize onto the VPU via one-hot matmuls or sort-free scatters
(``jax.ops.segment_sum``); everything jits, vmaps and shards.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_softmax", "sequence_reverse", "sequence_concat",
    "sequence_expand_as", "sequence_conv", "sequence_enumerate",
    "sequence_erase", "sequence_first_step", "sequence_last_step",
    "linear_chain_crf", "crf_decoding", "edit_distance", "ctc_align",
    "im2sequence",
]


# ---------------------------------------------------------------------------
# segment reductions (flat values + segment ids)
# ---------------------------------------------------------------------------

def segment_sum(data, segment_ids, num_segments: int):
    """Sum rows of ``data`` by segment (the LoD-free pooling substrate;
    reference ``operators/math/sequence_pooling.cu`` SumPool)."""
    return jax.ops.segment_sum(data, segment_ids,
                               num_segments=num_segments)


def segment_mean(data, segment_ids, num_segments: int):
    s = segment_sum(data, segment_ids, num_segments)
    n = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                            segment_ids, num_segments=num_segments)
    shape = (num_segments,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(n.reshape(shape), 1)


def segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(data, segment_ids,
                               num_segments=num_segments)


def segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(data, segment_ids,
                               num_segments=num_segments)


# ---------------------------------------------------------------------------
# padded-batch ops (dense [B, T, ...] + lengths [B])
# ---------------------------------------------------------------------------

def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    """[B] lengths → [B, maxlen] validity (reference
    ``sequence_ops/sequence_mask_op.h``)."""
    t = jnp.arange(maxlen, dtype=lengths.dtype)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def sequence_pad(flat, lengths, maxlen: int, pad_value=0.0):
    """Pack flat ragged rows ([total, ...] concatenated sequences with
    [B] lengths) into padded [B, maxlen, ...] (reference
    ``sequence_pad_op.cc``). ``total`` must equal ``sum(lengths)``; rows
    beyond each length take ``pad_value``."""
    B = lengths.shape[0]
    offsets = jnp.concatenate(
        [jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])
    pos = offsets[:, None] + jnp.arange(maxlen, dtype=lengths.dtype)[None]
    valid = sequence_mask(lengths, maxlen)
    safe = jnp.clip(pos, 0, flat.shape[0] - 1)
    out = flat[safe]                                   # [B, maxlen, ...]
    pad = jnp.asarray(pad_value, flat.dtype)
    return jnp.where(valid.reshape(valid.shape + (1,) * (flat.ndim - 1)),
                     out, pad)


def sequence_unpad(padded, lengths):
    """Padded [B, T, ...] → (flat [B*T, ...], flat_valid [B*T] bool,
    positions [B*T] int32) — the static-shape unpad (reference
    ``sequence_unpad_op.cc`` emits a dynamic [total] tensor; on TPU the
    capacity stays B*T and validity is explicit). ``positions`` maps each
    valid row to its index in the packed order (invalid rows map to the
    end), so ``flat[argsort(positions)]`` is packed order when needed."""
    B, T = padded.shape[:2]
    valid = sequence_mask(lengths, T).reshape(-1)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])
    pos_in_seq = jnp.broadcast_to(jnp.arange(T, dtype=lengths.dtype),
                                  (B, T))
    packed = (offsets[:, None] + pos_in_seq).reshape(-1)
    packed = jnp.where(valid, packed, B * T - 1).astype(jnp.int32)
    return padded.reshape((B * T,) + padded.shape[2:]), valid, packed


def sequence_pool(padded, lengths, pool_type: str = "sum"):
    """Pool valid timesteps per sequence: sum/mean/sqrt/max/min/first/
    last (reference ``sequence_pool_op.h`` + ``math/sequence_pooling``;
    ``sqrt`` divides the sum by sqrt(len), the reference's SSA pooling)."""
    B, T = padded.shape[:2]
    mask = sequence_mask(lengths, T)
    m = mask.reshape((B, T) + (1,) * (padded.ndim - 2))
    if pool_type == "sum":
        return jnp.sum(jnp.where(m, padded, 0), axis=1)
    if pool_type == "average" or pool_type == "mean":
        s = jnp.sum(jnp.where(m, padded, 0), axis=1)
        n = jnp.maximum(lengths, 1).astype(padded.dtype)
        return s / n.reshape((B,) + (1,) * (padded.ndim - 2))
    if pool_type == "sqrt":
        s = jnp.sum(jnp.where(m, padded, 0), axis=1)
        n = jnp.sqrt(jnp.maximum(lengths, 1).astype(padded.dtype))
        return s / n.reshape((B,) + (1,) * (padded.ndim - 2))
    if pool_type == "max":
        neg = jnp.finfo(padded.dtype).min if jnp.issubdtype(
            padded.dtype, jnp.floating) else jnp.iinfo(padded.dtype).min
        return jnp.max(jnp.where(m, padded, neg), axis=1)
    if pool_type == "min":
        pos = jnp.finfo(padded.dtype).max if jnp.issubdtype(
            padded.dtype, jnp.floating) else jnp.iinfo(padded.dtype).max
        return jnp.min(jnp.where(m, padded, pos), axis=1)
    if pool_type == "first":
        return padded[:, 0]
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            padded, idx.reshape((B, 1) + (1,) * (padded.ndim - 2)),
            axis=1)[:, 0]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(padded, lengths):
    return sequence_pool(padded, lengths, "first")


def sequence_last_step(padded, lengths):
    return sequence_pool(padded, lengths, "last")


def sequence_softmax(x, lengths):
    """Per-sequence masked softmax over the time axis of [B, T]
    (reference ``sequence_softmax_op.h``); padded positions get 0."""
    mask = sequence_mask(lengths, x.shape[1])
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask, x, neg)
    p = jax.nn.softmax(z, axis=1)
    return jnp.where(mask, p, 0.0)


def sequence_reverse(padded, lengths):
    """Reverse each sequence's valid prefix, padding stays in place
    (reference ``sequence_reverse_op.h``)."""
    B, T = padded.shape[:2]
    t = jnp.arange(T)
    idx = jnp.where(t[None, :] < lengths[:, None],
                    lengths[:, None] - 1 - t[None, :], t[None, :])
    return jnp.take_along_axis(
        padded, idx.reshape((B, T) + (1,) * (padded.ndim - 2)), axis=1)


def sequence_concat(a, a_len, b, b_len):
    """Concatenate two padded batches per-sequence (reference
    ``sequence_concat_op.h``): output [B, Ta+Tb, ...] with lengths
    a_len + b_len."""
    B, Ta = a.shape[:2]
    Tb = b.shape[1]
    T = Ta + Tb
    t = jnp.arange(T)
    from_a = t[None, :] < a_len[:, None]
    ia = jnp.broadcast_to(jnp.clip(t[None, :], 0, Ta - 1), (B, T))
    ib = jnp.clip(t[None, :] - a_len[:, None], 0, Tb - 1)
    ga = jnp.take_along_axis(
        a, ia.reshape((B, T) + (1,) * (a.ndim - 2)), axis=1)
    gb = jnp.take_along_axis(
        b, ib.reshape((B, T) + (1,) * (b.ndim - 2)), axis=1)
    out = jnp.where(from_a.reshape((B, T) + (1,) * (a.ndim - 2)), ga, gb)
    new_len = a_len + b_len
    mask = sequence_mask(new_len, T)
    return jnp.where(mask.reshape((B, T) + (1,) * (a.ndim - 2)), out,
                     jnp.zeros((), a.dtype)), new_len


def sequence_expand_as(x, lengths, maxlen: int):
    """Broadcast one row per sequence across its timesteps (reference
    ``sequence_expand_as_op.h``): x [B, ...] → [B, maxlen, ...] masked to
    the lengths."""
    out = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen) + x.shape[1:])
    mask = sequence_mask(lengths, maxlen)
    return jnp.where(
        mask.reshape(mask.shape + (1,) * (x.ndim - 1)), out,
        jnp.zeros((), x.dtype))


def sequence_conv(padded, lengths, filter_w, context_start: int = -1,
                  context_length: int = 3):
    """Contextual (time-window) projection (reference
    ``sequence_conv_op.h``: im2col over the context window then GEMM).
    padded [B, T, E]; filter_w [context_length*E, O]; out [B, T, O];
    positions outside the sequence contribute zeros."""
    B, T, E = padded.shape
    mask = sequence_mask(lengths, T)
    x = jnp.where(mask[..., None], padded, 0)
    cols = []
    for j in range(context_length):
        off = context_start + j
        shifted = jnp.roll(x, -off, axis=1)
        t = jnp.arange(T)
        ok = (t[None, :] + off >= 0) & (t[None, :] + off < lengths[:, None])
        cols.append(jnp.where(ok[..., None], shifted, 0))
    ctx = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*E]
    out = ctx @ filter_w
    return jnp.where(mask[..., None], out, 0)


def sequence_enumerate(ids, lengths, win_size: int, pad_value: int = 0):
    """Sliding windows of ids per sequence (reference
    ``sequence_enumerate_op.h``): [B, T] → [B, T, win_size]; positions
    past the sequence end take ``pad_value``."""
    B, T = ids.shape
    t = jnp.arange(T)
    out = []
    for j in range(win_size):
        shifted = jnp.roll(ids, -j, axis=1)
        ok = t[None, :] + j < lengths[:, None]
        out.append(jnp.where(ok, shifted, pad_value))
    return jnp.stack(out, axis=-1)


def _left_compact(ids, keep, length_dtype):
    """Keep-masked tokens, left-compacted per row (stable order):
    returns ([B, T] zero-padded, new lengths). Dropped tokens target
    index T → out-of-bounds → ``mode="drop"`` skips the write; only kept
    ids land, at their cumsum-compacted slots."""
    B, T = ids.shape
    new_pos = jnp.cumsum(keep, axis=1) - 1                 # [B, T]
    new_len = jnp.sum(keep, axis=1).astype(length_dtype)
    b = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    tgt = jnp.where(keep, new_pos, T)
    out = jnp.zeros_like(ids).at[b, tgt].set(ids, mode="drop")
    return out, new_len


def sequence_erase(ids, lengths, tokens):
    """Remove every occurrence of ``tokens`` and left-compact each
    sequence (reference ``sequence_erase_op.h``). Static shapes: output
    [B, T] with ``pad`` (0) tail and the new lengths."""
    tokens = jnp.asarray(tokens)
    valid = sequence_mask(lengths, ids.shape[1])
    keep = valid & ~jnp.isin(ids, tokens)
    return _left_compact(ids, keep, lengths.dtype)


# ---------------------------------------------------------------------------
# sequence labeling: CRF, edit distance, CTC alignment, im2sequence
# (reference operators/linear_chain_crf_op.*, crf_decoding_op.*,
# edit_distance_op.*, ctc_align_op.*, im2sequence_op.*)
# ---------------------------------------------------------------------------

def linear_chain_crf(emission, transition, labels, lengths):
    """Per-sequence negative log-likelihood of a linear-chain CRF
    (reference ``linear_chain_crf_op.h``; same transition layout:
    ``transition[0]`` = start weights, ``transition[1]`` = stop weights,
    ``transition[2:]`` = the [D, D] transition matrix w[prev, next]).

    TPU-native formulation: the reference normalizes per-step in the
    probability domain (``NormalizeL1``); here the forward algorithm runs
    in the log domain with a ``lax.scan`` over time — algebraically the
    same partition function, MXU/VPU-friendly and stable without
    normalization. Inputs are the padded encoding: emission [B, T, D],
    labels [B, T] int, lengths [B]. Returns nll [B].
    """
    emission = jnp.asarray(emission)
    B, T, D = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    valid = sequence_mask(lengths, T)                       # [B, T]

    # log partition via forward recursion
    alpha0 = start[None, :] + emission[:, 0]                # [B, D]

    def fwd(alpha, t):
        e_t = emission[:, t]                                # [B, D]
        new = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + e_t
        alpha = jnp.where(valid[:, t][:, None], new, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, T)) if T > 1 \
        else (alpha0, None)
    log_z = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=-1)

    # gold-path score: start + emissions + transitions + stop, masked
    lab = jnp.clip(labels, 0, D - 1)
    b = jnp.arange(B)
    e_score = jnp.sum(
        jnp.where(valid, jnp.take_along_axis(
            emission, lab[:, :, None], axis=2)[:, :, 0], 0.0), axis=1)
    pair_valid = valid[:, 1:]                               # step t-1 → t
    t_score = jnp.sum(
        jnp.where(pair_valid, trans[lab[:, :-1], lab[:, 1:]], 0.0),
        axis=1) if T > 1 else jnp.zeros((B,), emission.dtype)
    last = jnp.clip(lengths - 1, 0, T - 1)
    gold = (start[lab[:, 0]] + e_score + t_score
            + stop[lab[b, last]])
    return log_z - gold


def crf_decoding(emission, transition, lengths, labels=None):
    """Viterbi decode (reference ``crf_decoding_op.h``): best path
    [B, T] (zeros past each length). With ``labels``, returns instead the
    reference's per-position correctness indicator — 1 where the decoded
    tag equals the label within the sequence, 0 elsewhere."""
    emission = jnp.asarray(emission)
    B, T, D = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    valid = sequence_mask(lengths, T)

    v0 = start[None, :] + emission[:, 0]                    # [B, D]

    def step(v, t):
        scores = v[:, :, None] + trans[None]                # [B, D, D]
        best_prev = jnp.argmax(scores, axis=1)              # [B, D]
        new = jnp.max(scores, axis=1) + emission[:, t]
        keep = valid[:, t][:, None]
        v = jnp.where(keep, new, v)
        # frozen steps point back at themselves (identity backpointer)
        bp = jnp.where(keep, best_prev,
                       jnp.broadcast_to(jnp.arange(D)[None], (B, D)))
        return v, bp

    if T > 1:
        v, bps = jax.lax.scan(step, v0, jnp.arange(1, T))   # bps [T-1,B,D]
    else:
        v, bps = v0, jnp.zeros((0, B, D), jnp.int32)
    last_tag = jnp.argmax(v + stop[None, :], axis=-1)       # [B]

    # backtrace: bps[k] holds, for position k+1, the best tag at position
    # k. reverse scan carries the tag backwards; frozen (past-length)
    # steps have identity backpointers so the final real tag propagates
    # unchanged through the padding region.
    def back(tag, bp):
        prev = bp[jnp.arange(B), tag]
        return prev, tag              # emit the tag at position k+1

    first_tag, rest = jax.lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([first_tag[:, None], rest.T], axis=1) \
        if T > 1 else last_tag[:, None]
    path = jnp.where(valid, path, 0)
    if labels is not None:
        return jnp.where(valid, (path == labels).astype(jnp.int32), 0)
    return path


def edit_distance(hyp, hyp_len, ref, ref_len, normalized: bool = False):
    """Levenshtein distance per pair (reference ``edit_distance_op.h``):
    hyp [B, Th] int, ref [B, Tr] int with lengths; ``normalized`` divides
    by the reference length. Wavefront DP as a ``lax.scan`` over hyp
    positions carrying one [Tr+1] row per sequence (vmapped over B)."""
    hyp, ref = jnp.asarray(hyp), jnp.asarray(ref)
    Th, Tr = hyp.shape[1], ref.shape[1]

    def one(h, hl, r, rl):
        idx = jnp.arange(Tr + 1, dtype=jnp.float32)  # also the DP row 0

        def step(row, i):
            # row = distances for hyp[:i]; compute for hyp[:i+1]. The
            # left-to-right recurrence new[j] = min(base_j, new[j-1]+1)
            # is a (min,+) running min: new[j] = j + cummin(base - j) —
            # log-depth on TPU instead of Tr sequential scalar steps.
            ins = row[1:] + 1.0
            sub = row[:-1] + (h[i] != r).astype(jnp.float32)
            base = jnp.concatenate([row[:1] + 1.0,
                                    jnp.minimum(ins, sub)])
            new = idx + jax.lax.cummin(base - idx)
            return jnp.where(i < hl, new, row), None

        row, _ = jax.lax.scan(step, idx, jnp.arange(Th))
        # (rl == 0 needs no special case: row[0] accumulates +1 per valid
        # hyp step, so it already equals hl there)
        d = row[jnp.clip(rl, 0, Tr)]
        if normalized:
            d = d / jnp.maximum(rl.astype(jnp.float32), 1.0)
        return d

    return jax.vmap(one)(hyp, hyp_len, ref, ref_len)


def ctc_align(ids, lengths, blank: int = 0):
    """CTC greedy-decode alignment (reference ``ctc_align_op.h``): merge
    repeated tokens, drop blanks, left-compact. Returns (aligned [B, T]
    zero-padded, new_lengths [B])."""
    ids = jnp.asarray(ids)
    B, T = ids.shape
    valid = sequence_mask(lengths, T)
    prev = jnp.concatenate([jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]],
                           axis=1)
    keep = valid & (ids != blank) & (ids != prev)
    return _left_compact(ids, keep, lengths.dtype)


def im2sequence(x, kernel_size, stride=1, padding=0):
    """[N, C, H, W] → [N, L, C*kh*kw] patch sequence (reference
    ``im2sequence_op.h``, the OCR feeder): each output step is one
    flattened receptive field, row-major over output positions."""
    from paddle_tpu.nn import functional as F

    cols = F.unfold(x, kernel_size, stride=stride, padding=padding)
    return cols.transpose(0, 2, 1)                          # [N, L, C*k*k]
