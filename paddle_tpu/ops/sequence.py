"""Sequence / ragged operators — the LoD op family, TPU-native.

The reference handles variable-length data with LoD tensors and ~20
per-sequence CPU/CUDA loops under
``paddle/fluid/operators/sequence_ops/`` (``sequence_pool_op.cc``,
``sequence_conv_op.cc``, ``sequence_pad_op.cc``, …, plus
``operators/math/sequence_pooling.cu``). LoD — a host-side list of
offsets changing per batch — cannot exist under XLA's static shapes, so
the TPU representation is **(padded dense, lengths)** for batched data
and **(flat values, segment_ids)** for fully ragged data; every op here
is a masked static-shape computation over one of those two encodings.

Segment reductions are the ``SelectedRows``/sequence-pooling analogue
and vectorize onto the VPU via one-hot matmuls or sort-free scatters
(``jax.ops.segment_sum``); everything jits, vmaps and shards.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_softmax", "sequence_reverse", "sequence_concat",
    "sequence_expand_as", "sequence_conv", "sequence_enumerate",
    "sequence_erase", "sequence_first_step", "sequence_last_step",
]


# ---------------------------------------------------------------------------
# segment reductions (flat values + segment ids)
# ---------------------------------------------------------------------------

def segment_sum(data, segment_ids, num_segments: int):
    """Sum rows of ``data`` by segment (the LoD-free pooling substrate;
    reference ``operators/math/sequence_pooling.cu`` SumPool)."""
    return jax.ops.segment_sum(data, segment_ids,
                               num_segments=num_segments)


def segment_mean(data, segment_ids, num_segments: int):
    s = segment_sum(data, segment_ids, num_segments)
    n = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                            segment_ids, num_segments=num_segments)
    shape = (num_segments,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(n.reshape(shape), 1)


def segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(data, segment_ids,
                               num_segments=num_segments)


def segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(data, segment_ids,
                               num_segments=num_segments)


# ---------------------------------------------------------------------------
# padded-batch ops (dense [B, T, ...] + lengths [B])
# ---------------------------------------------------------------------------

def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    """[B] lengths → [B, maxlen] validity (reference
    ``sequence_ops/sequence_mask_op.h``)."""
    t = jnp.arange(maxlen, dtype=lengths.dtype)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def sequence_pad(flat, lengths, maxlen: int, pad_value=0.0):
    """Pack flat ragged rows ([total, ...] concatenated sequences with
    [B] lengths) into padded [B, maxlen, ...] (reference
    ``sequence_pad_op.cc``). ``total`` must equal ``sum(lengths)``; rows
    beyond each length take ``pad_value``."""
    B = lengths.shape[0]
    offsets = jnp.concatenate(
        [jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])
    pos = offsets[:, None] + jnp.arange(maxlen, dtype=lengths.dtype)[None]
    valid = sequence_mask(lengths, maxlen)
    safe = jnp.clip(pos, 0, flat.shape[0] - 1)
    out = flat[safe]                                   # [B, maxlen, ...]
    pad = jnp.asarray(pad_value, flat.dtype)
    return jnp.where(valid.reshape(valid.shape + (1,) * (flat.ndim - 1)),
                     out, pad)


def sequence_unpad(padded, lengths):
    """Padded [B, T, ...] → (flat [B*T, ...], flat_valid [B*T] bool,
    positions [B*T] int32) — the static-shape unpad (reference
    ``sequence_unpad_op.cc`` emits a dynamic [total] tensor; on TPU the
    capacity stays B*T and validity is explicit). ``positions`` maps each
    valid row to its index in the packed order (invalid rows map to the
    end), so ``flat[argsort(positions)]`` is packed order when needed."""
    B, T = padded.shape[:2]
    valid = sequence_mask(lengths, T).reshape(-1)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])
    pos_in_seq = jnp.broadcast_to(jnp.arange(T, dtype=lengths.dtype),
                                  (B, T))
    packed = (offsets[:, None] + pos_in_seq).reshape(-1)
    packed = jnp.where(valid, packed, B * T - 1).astype(jnp.int32)
    return padded.reshape((B * T,) + padded.shape[2:]), valid, packed


def sequence_pool(padded, lengths, pool_type: str = "sum"):
    """Pool valid timesteps per sequence: sum/mean/sqrt/max/min/first/
    last (reference ``sequence_pool_op.h`` + ``math/sequence_pooling``;
    ``sqrt`` divides the sum by sqrt(len), the reference's SSA pooling)."""
    B, T = padded.shape[:2]
    mask = sequence_mask(lengths, T)
    m = mask.reshape((B, T) + (1,) * (padded.ndim - 2))
    if pool_type == "sum":
        return jnp.sum(jnp.where(m, padded, 0), axis=1)
    if pool_type == "average" or pool_type == "mean":
        s = jnp.sum(jnp.where(m, padded, 0), axis=1)
        n = jnp.maximum(lengths, 1).astype(padded.dtype)
        return s / n.reshape((B,) + (1,) * (padded.ndim - 2))
    if pool_type == "sqrt":
        s = jnp.sum(jnp.where(m, padded, 0), axis=1)
        n = jnp.sqrt(jnp.maximum(lengths, 1).astype(padded.dtype))
        return s / n.reshape((B,) + (1,) * (padded.ndim - 2))
    if pool_type == "max":
        neg = jnp.finfo(padded.dtype).min if jnp.issubdtype(
            padded.dtype, jnp.floating) else jnp.iinfo(padded.dtype).min
        return jnp.max(jnp.where(m, padded, neg), axis=1)
    if pool_type == "min":
        pos = jnp.finfo(padded.dtype).max if jnp.issubdtype(
            padded.dtype, jnp.floating) else jnp.iinfo(padded.dtype).max
        return jnp.min(jnp.where(m, padded, pos), axis=1)
    if pool_type == "first":
        return padded[:, 0]
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            padded, idx.reshape((B, 1) + (1,) * (padded.ndim - 2)),
            axis=1)[:, 0]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(padded, lengths):
    return sequence_pool(padded, lengths, "first")


def sequence_last_step(padded, lengths):
    return sequence_pool(padded, lengths, "last")


def sequence_softmax(x, lengths):
    """Per-sequence masked softmax over the time axis of [B, T]
    (reference ``sequence_softmax_op.h``); padded positions get 0."""
    mask = sequence_mask(lengths, x.shape[1])
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask, x, neg)
    p = jax.nn.softmax(z, axis=1)
    return jnp.where(mask, p, 0.0)


def sequence_reverse(padded, lengths):
    """Reverse each sequence's valid prefix, padding stays in place
    (reference ``sequence_reverse_op.h``)."""
    B, T = padded.shape[:2]
    t = jnp.arange(T)
    idx = jnp.where(t[None, :] < lengths[:, None],
                    lengths[:, None] - 1 - t[None, :], t[None, :])
    return jnp.take_along_axis(
        padded, idx.reshape((B, T) + (1,) * (padded.ndim - 2)), axis=1)


def sequence_concat(a, a_len, b, b_len):
    """Concatenate two padded batches per-sequence (reference
    ``sequence_concat_op.h``): output [B, Ta+Tb, ...] with lengths
    a_len + b_len."""
    B, Ta = a.shape[:2]
    Tb = b.shape[1]
    T = Ta + Tb
    t = jnp.arange(T)
    from_a = t[None, :] < a_len[:, None]
    ia = jnp.broadcast_to(jnp.clip(t[None, :], 0, Ta - 1), (B, T))
    ib = jnp.clip(t[None, :] - a_len[:, None], 0, Tb - 1)
    ga = jnp.take_along_axis(
        a, ia.reshape((B, T) + (1,) * (a.ndim - 2)), axis=1)
    gb = jnp.take_along_axis(
        b, ib.reshape((B, T) + (1,) * (b.ndim - 2)), axis=1)
    out = jnp.where(from_a.reshape((B, T) + (1,) * (a.ndim - 2)), ga, gb)
    new_len = a_len + b_len
    mask = sequence_mask(new_len, T)
    return jnp.where(mask.reshape((B, T) + (1,) * (a.ndim - 2)), out,
                     jnp.zeros((), a.dtype)), new_len


def sequence_expand_as(x, lengths, maxlen: int):
    """Broadcast one row per sequence across its timesteps (reference
    ``sequence_expand_as_op.h``): x [B, ...] → [B, maxlen, ...] masked to
    the lengths."""
    out = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen) + x.shape[1:])
    mask = sequence_mask(lengths, maxlen)
    return jnp.where(
        mask.reshape(mask.shape + (1,) * (x.ndim - 1)), out,
        jnp.zeros((), x.dtype))


def sequence_conv(padded, lengths, filter_w, context_start: int = -1,
                  context_length: int = 3):
    """Contextual (time-window) projection (reference
    ``sequence_conv_op.h``: im2col over the context window then GEMM).
    padded [B, T, E]; filter_w [context_length*E, O]; out [B, T, O];
    positions outside the sequence contribute zeros."""
    B, T, E = padded.shape
    mask = sequence_mask(lengths, T)
    x = jnp.where(mask[..., None], padded, 0)
    cols = []
    for j in range(context_length):
        off = context_start + j
        shifted = jnp.roll(x, -off, axis=1)
        t = jnp.arange(T)
        ok = (t[None, :] + off >= 0) & (t[None, :] + off < lengths[:, None])
        cols.append(jnp.where(ok[..., None], shifted, 0))
    ctx = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*E]
    out = ctx @ filter_w
    return jnp.where(mask[..., None], out, 0)


def sequence_enumerate(ids, lengths, win_size: int, pad_value: int = 0):
    """Sliding windows of ids per sequence (reference
    ``sequence_enumerate_op.h``): [B, T] → [B, T, win_size]; positions
    past the sequence end take ``pad_value``."""
    B, T = ids.shape
    t = jnp.arange(T)
    out = []
    for j in range(win_size):
        shifted = jnp.roll(ids, -j, axis=1)
        ok = t[None, :] + j < lengths[:, None]
        out.append(jnp.where(ok, shifted, pad_value))
    return jnp.stack(out, axis=-1)


def sequence_erase(ids, lengths, tokens):
    """Remove every occurrence of ``tokens`` and left-compact each
    sequence (reference ``sequence_erase_op.h``). Static shapes: output
    [B, T] with ``pad`` (0) tail and the new lengths."""
    B, T = ids.shape
    tokens = jnp.asarray(tokens)
    valid = sequence_mask(lengths, T)
    keep = valid & ~jnp.isin(ids, tokens)
    # left-compact: stable order of kept tokens via cumsum positions
    new_pos = jnp.cumsum(keep, axis=1) - 1                 # [B, T]
    new_len = jnp.sum(keep, axis=1).astype(lengths.dtype)
    b = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    # dropped tokens target index T → out-of-bounds → mode="drop" skips
    # the write; only kept ids land, at their compacted slots
    tgt = jnp.where(keep, new_pos, T)
    out = jnp.zeros_like(ids).at[b, tgt].set(ids, mode="drop")
    return out, new_len
