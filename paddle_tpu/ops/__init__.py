"""Hand-written TPU kernels (Pallas) for the hot-op set.

The reference implements its hot set as CUDA kernels under
``paddle/fluid/operators/fused/`` (``multihead_matmul_op.cu``,
``skip_layernorm_op.cu``), ``operators/math/softmax.cu`` and
``operators/optimizers/adam_op.cu``. Here the equivalents are Pallas
kernels tiled for the MXU/VPU; everything else stays jax.numpy and lets
XLA fuse.
"""

from paddle_tpu.ops import extras  # noqa: F401
from paddle_tpu.ops import pallas  # noqa: F401
from paddle_tpu.ops import sequence  # noqa: F401

__all__ = ["pallas", "sequence", "extras"]
