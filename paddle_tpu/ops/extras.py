"""Contrib / long-tail operators from the reference's op zoo.

The ops here are the audited tail of ``OPS_AUDIT.md`` — small math ops
the reference registers as individual CUDA/CPU kernels under
``paddle/fluid/operators/``, expressed as jnp compositions XLA fuses on
its own (none is hot enough to justify a Pallas kernel). Each docstring
cites the reference op it matches.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "shuffle_channel", "temporal_shift", "space_to_depth",
    "add_position_encoding", "multiplex", "partial_concat", "partial_sum",
    "cvm", "gather_tree", "fsp_matrix", "conv_shift", "batch_fc",
    "max_pool2d_with_index", "max_unpool2d", "spatial_pyramid_pool",
    "hinge_loss", "rank_loss", "bpr_loss", "center_loss", "huber_loss",
    "modified_huber_loss", "teacher_student_sigmoid_loss",
    "squared_l2_distance", "squared_l2_norm", "l1_norm",
]


# ---------------------------------------------------------------------------
# feature-map / tensor transforms
# ---------------------------------------------------------------------------

def shuffle_channel(x, groups: int):
    """ShuffleNet channel shuffle on NCHW (reference
    ``operators/shuffle_channel_op.cc``): split C into ``groups``,
    transpose the (group, sub) axes."""
    n, c, h, w = x.shape
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    return (x.reshape(n, groups, c // groups, h, w)
            .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w))


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25):
    """TSM temporal shift on [N*T, C, H, W] (reference
    ``operators/temporal_shift_op.cc``): the first ``shift_ratio`` of
    channels shift one step back in time, the next ``shift_ratio``
    forward, the rest stay."""
    nt, c, h, w = x.shape
    if nt % seg_num:
        raise ValueError(f"batch {nt} not divisible by seg_num {seg_num}")
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    pad = jnp.pad(x5, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
    back = pad[:, 2:, :c1]            # channel group 1: t+1 -> t
    fwd = pad[:, :-2, c1:c2]          # channel group 2: t-1 -> t
    keep = x5[:, :, c2:]
    return jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)


def space_to_depth(x, blocksize: int):
    """Rearrange NCHW spatial blocks into channels (reference
    ``operators/space_to_depth_op.cc``); ``F.pixel_shuffle`` is the
    inverse direction."""
    n, c, h, w = x.shape
    b = blocksize
    if h % b or w % b:
        raise ValueError(f"spatial dims ({h},{w}) not divisible by {b}")
    x = x.reshape(n, c, h // b, b, w // b, b)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(
        n, c * b * b, h // b, w // b)


def add_position_encoding(x, alpha: float = 1.0, beta: float = 1.0):
    """Scaled input + sinusoidal position table (reference
    ``operators/add_position_encoding_op.cc``): out = alpha*x + beta*PE
    for x [B, T, E]."""
    _, t, e = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = (e + 1) // 2                   # sin gets the extra odd column
    div = jnp.exp(jnp.arange(half, dtype=jnp.float32)
                  * -(math.log(10000.0) / max(half - 1, 1)))
    pe = jnp.concatenate(
        [jnp.sin(pos * div), jnp.cos(pos * div[:e - half])], axis=1)
    return alpha * x + beta * pe[None].astype(x.dtype)


def multiplex(inputs, index):
    """Row-select across a list of same-shape tensors (reference
    ``operators/multiplex_op.cc``): out[i] = inputs[index[i]][i]."""
    stacked = jnp.stack(inputs)                        # [K, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def partial_concat(xs, start_index: int = 0, length: int = -1):
    """Concat column slices of 2-D inputs (reference
    ``operators/partial_concat_op.cc``)."""
    end = None if length < 0 else start_index + length
    return jnp.concatenate([x[:, start_index:end] for x in xs], axis=1)


def partial_sum(xs, start_index: int = 0, length: int = -1):
    """Sum column slices of 2-D inputs (reference
    ``operators/partial_sum_op.cc``)."""
    end = None if length < 0 else start_index + length
    out = xs[0][:, start_index:end]
    for x in xs[1:]:
        out = out + x[:, start_index:end]
    return out


def cvm(x, use_cvm: bool = True):
    """CTR show/click feature transform (reference
    ``operators/cvm_op.h`` CvmComputeKernel): x [N, D] whose first two
    columns are (show, click). use_cvm=True keeps them as
    (log(show+1), log(click+1) - log(show+1)); False drops them."""
    if not use_cvm:
        return x[:, 2:]
    show = jnp.log(x[:, :1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    return jnp.concatenate([show, click, x[:, 2:]], axis=1)


def gather_tree(ids, parents):
    """Backtrace beam-search ancestry (reference
    ``operators/gather_tree_op.cc``): ids/parents [T, B, K]; returns the
    full sequences selected by the last step's beams."""
    T = ids.shape[0]

    def body(carry, xs):
        beam = carry                                   # [B, K]
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, beam, axis=1)
        beam = jnp.take_along_axis(step_parents, beam, axis=1)
        return beam, out

    init = jnp.broadcast_to(jnp.arange(ids.shape[2], dtype=ids.dtype),
                            ids.shape[1:])
    _, rev = jax.lax.scan(body, init, (ids[::-1], parents[::-1]))
    return rev[::-1]


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (reference
    ``operators/fsp_op.cc``): x [N, C1, H, W], y [N, C2, H, W] →
    [N, C1, C2] normalized channel correlation."""
    n, c1, h, w = x.shape
    xf = x.reshape(n, c1, h * w)
    yf = y.reshape(n, y.shape[1], h * w)
    return jnp.einsum("ncs,nds->ncd", xf, yf) / (h * w)


def conv_shift(x, y):
    """Circular correlation (NTM addressing; reference
    ``operators/conv_shift_op.cc``): x [B, M], y [B, N] (N odd, N<=M):
    out[i] = sum_j x[(i + j - (N-1)/2) mod M] * y[j]."""
    m, nsh = x.shape[1], y.shape[1]
    half = (nsh - 1) // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(nsh)[None, :] - half) % m
    return jnp.einsum("bmn,bn->bm", x[:, idx], y)


def batch_fc(x, w, bias=None):
    """Per-slot batched FC (reference ``operators/batch_fc_op.cc``):
    x [S, N, I], w [S, I, O], bias [S, O] → [S, N, O]."""
    out = jnp.einsum("sni,sio->sno", x, w)
    if bias is not None:
        out = out + bias[:, None, :]
    return out


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0):
    """Max pooling returning flat argmax indices into each input map
    (reference ``operators/max_pool2d_with_index`` /
    ``pool_with_index_op.cc``) — the indices feed ``max_unpool2d``."""
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride))
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    n, c, h, w = x.shape
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])),
                 constant_values=neg)
    # index map padded alongside, -1 marking padding
    flat_idx = (jnp.arange(h * w, dtype=jnp.int32).reshape(h, w))
    ip = jnp.pad(flat_idx, ((pd[0], pd[0]), (pd[1], pd[1])),
                 constant_values=-1)
    oh = (h + 2 * pd[0] - ks[0]) // st[0] + 1
    ow = (w + 2 * pd[1] - ks[1]) // st[1] + 1
    # window extraction via gather of strided patches
    r0 = jnp.arange(oh) * st[0]
    c0 = jnp.arange(ow) * st[1]
    rows = r0[:, None, None, None] + jnp.arange(ks[0])[None, None, :, None]
    cols = c0[None, :, None, None] + jnp.arange(ks[1])[None, None, None, :]
    patches = xp[:, :, rows, cols]          # [N, C, oh, ow, kh, kw]
    pidx = ip[rows, cols]                   # [oh, ow, kh, kw]
    pf = patches.reshape(n, c, oh, ow, -1)
    arg = jnp.argmax(pf, axis=-1)
    out = jnp.take_along_axis(pf, arg[..., None], axis=-1)[..., 0]
    idx = jnp.broadcast_to(pidx.reshape(oh, ow, -1)[None, None], pf.shape)
    sel = jnp.take_along_axis(idx, arg[..., None], axis=-1)[..., 0]
    return out, sel.astype(jnp.int32)


def max_unpool2d(x, indices, output_size):
    """Scatter pooled values back to their argmax positions (reference
    ``operators/unpool_op.cc``): x/indices [N, C, oh, ow], flat indices
    into the [H, W] output maps."""
    n, c, oh, ow = x.shape
    H, W = output_size
    flat = jnp.zeros((n, c, H * W), x.dtype)
    idx = indices.reshape(n, c, -1)
    vals = x.reshape(n, c, -1)
    flat = flat.at[jnp.arange(n)[:, None, None],
                   jnp.arange(c)[None, :, None], idx].add(vals)
    return flat.reshape(n, c, H, W)


def spatial_pyramid_pool(x, pyramid_height: int, pool_type: str = "max"):
    """SPP head (reference ``operators/spp_op.cc``): concat pooled
    [1x1, 2x2, ..., 2^(h-1) x 2^(h-1)] grids of NCHW into [N, C*sum]."""
    from paddle_tpu.nn import functional as F

    n, c = x.shape[:2]
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        if pool_type == "max":
            p = F.adaptive_max_pool2d(x, bins)
        else:
            p = F.adaptive_avg_pool2d(x, bins)
        outs.append(p.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# long-tail losses
# ---------------------------------------------------------------------------

def hinge_loss(logits, labels):
    """Elementwise hinge (reference ``operators/hinge_loss_op.cc``):
    max(0, 1 - (2y - 1) * x)."""
    return jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)


def rank_loss(label, left, right):
    """RankNet pairwise loss (reference ``operators/rank_loss_op.cc``):
    C = log(1 + exp(o)) - P*o with o = left - right."""
    o = left - right
    return jnp.logaddexp(0.0, o) - label * o


def bpr_loss(x, label):
    """Bayesian personalized ranking (reference
    ``operators/bpr_loss_op.cc``): x [N, C] scores, label [N] the
    positive item; mean over negatives of -log(sigmoid(x_pos - x_j))."""
    n, c = x.shape
    pos = jnp.take_along_axis(x, label[:, None].astype(jnp.int32),
                              axis=1)                   # [N, 1]
    diff = pos - x
    lo = jax.nn.log_sigmoid(diff)
    mask = jnp.ones((n, c), bool).at[jnp.arange(n),
                                     label.astype(jnp.int32)].set(False)
    return -jnp.sum(lo * mask, axis=1) / jnp.maximum(c - 1, 1)


def center_loss(features, label, centers, alpha: float = 0.1,
                update: bool = True):
    """Center loss (reference ``operators/center_loss_op.cc``): pulls
    features toward their class centers. Returns (per-sample loss,
    new_centers) — the center update is functional here (the reference
    mutates the centers buffer in-kernel)."""
    label = label.astype(jnp.int32)
    cent = centers[label]                              # [N, E]
    diff = features - cent
    loss = 0.5 * jnp.sum(diff * diff, axis=1)
    if not update:
        return loss, centers
    num = jnp.zeros((centers.shape[0],), jnp.float32).at[label].add(1.0)
    delta = jnp.zeros_like(centers).at[label].add(diff.astype(centers.dtype))
    new_centers = centers + alpha * delta / (num[:, None] + 1.0)
    return loss, new_centers


def huber_loss(x, y, delta: float = 1.0):
    """Huber regression loss (reference ``operators/huber_loss_op.cc``)."""
    r = jnp.abs(x - y)
    return jnp.where(r <= delta, 0.5 * r * r,
                     delta * (r - 0.5 * delta))


def modified_huber_loss(x, y):
    """Classification Huber (reference
    ``operators/modified_huber_loss_op.cc``): z = (2y-1)*x;
    max(0, 1-z)^2 for z >= -1, else -4z."""
    z = (2.0 * y - 1.0) * x
    sq = jnp.square(jnp.maximum(0.0, 1.0 - z))
    return jnp.where(z >= -1.0, sq, -4.0 * z)


def teacher_student_sigmoid_loss(x, label):
    """Distillation sigmoid loss (reference
    ``operators/teacher_student_sigmoid_loss_op.cc``): the label packs
    click z and teacher score z' (label<-1: z=0 no teacher; label<0:
    z=1 no teacher; 0<=label<1: z=0, z'=label; label>=1: z=1,
    z'=label-1); loss = xent(x, z) + xent(x, z') where present."""
    x = x.reshape(-1)
    label = label.reshape(-1)
    softplus = jnp.logaddexp(0.0, -jnp.abs(x))
    base = jnp.maximum(x, 0.0) + softplus

    z = jnp.where(label < -1.0, 0.0,
                  jnp.where(label < 0.0, 1.0,
                            jnp.where(label < 1.0, 0.0, 1.0)))
    has_teacher = label >= 0.0
    zprime = jnp.where(label < 1.0, label, label - 1.0)
    student = base - x * z
    teacher = jnp.where(has_teacher, base - x * zprime, 0.0)
    return student + teacher


def squared_l2_distance(x, y):
    """Row-wise squared L2 distance (reference
    ``operators/squared_l2_distance_op.cc``)."""
    d = (x - y).reshape(x.shape[0], -1)
    return jnp.sum(d * d, axis=1)


def squared_l2_norm(x):
    """Reference ``operators/squared_l2_norm_op.cc``."""
    return jnp.sum(jnp.square(x))


def l1_norm(x):
    """Reference ``operators/l1_norm_op.cc``."""
    return jnp.sum(jnp.abs(x))
