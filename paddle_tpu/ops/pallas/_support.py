"""Shared dispatch helpers for the Pallas kernel set.

Kernels compile only for the TPU backend; on CPU they run through the
Pallas interpreter (bit-accurate, slow) — used by the OpTest-style unit
tests. The ``interpret()`` switch below decides per-call.
"""

from __future__ import annotations

import jax

_FORCE_INTERPRET = False


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def single_device() -> bool:
    """True when no multi-device mesh is active. pallas_call carries no
    GSPMD partitioning rule, so under a >1-device jit the partitioner
    would replicate operands (or fail to lower) — auto-dispatch must fall
    back to the jnp path there. Multi-device flash attention instead goes
    through the shard_map sequence-parallel path
    (``paddle_tpu/parallel/ring_attention.py``), where per-device shapes
    make the kernel safe."""
    from paddle_tpu.parallel import mesh as M

    mesh = M.current_mesh()
    return mesh is None or mesh.size <= 1


def auto_dispatch() -> bool:
    """Default ('auto') dispatch gate for the kernel set."""
    return on_tpu() and single_device()


def interpret() -> bool:
    """Whether pallas_call should run in interpreter mode."""
    return _FORCE_INTERPRET or not on_tpu()


def compiler_params(**kwargs):
    """TPU compiler params, or None off-TPU/interpret (ignored there)."""
    if interpret():
        return None
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)


class force_interpret:
    """Context manager: run all paddle_tpu Pallas kernels interpreted."""

    def __enter__(self):
        global _FORCE_INTERPRET
        self._prev = _FORCE_INTERPRET
        _FORCE_INTERPRET = True

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._prev
        return False
