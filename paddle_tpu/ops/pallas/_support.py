"""Shared dispatch helpers for the Pallas kernel set.

Kernels compile only for the TPU backend; on CPU they run through the
Pallas interpreter (bit-accurate, slow) — used by the OpTest-style unit
tests. The ``interpret()`` switch below decides per-call.
"""

from __future__ import annotations

import jax

_FORCE_INTERPRET = False
_FORCE_DISPATCH = False


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def single_device() -> bool:
    """True when no multi-device mesh is active."""
    from paddle_tpu.parallel import mesh as M

    mesh = M.current_mesh()
    return mesh is None or mesh.size <= 1


def _manual_axes():
    """(any_manual, all_manual) over the ambient abstract mesh axes."""
    try:
        am = jax.sharding.get_abstract_mesh()
    except Exception:
        return False, False
    if am is None or not am.shape:
        return False, False
    manual = [t == jax.sharding.AxisType.Manual for t in am.axis_types]
    return any(manual), all(manual)


def dispatch_mode() -> str:
    """How the kernel set should dispatch at this trace point.

    - ``"off"`` — stay on the jnp path (not on TPU, or inside a
      partially-manual shard_map where neither raw local shapes nor
      custom_partitioning are safe).
    - ``"raw"`` — call pallas directly: single-device jit, or inside a
      fully-manual shard_map where shapes are already per-device (the
      Ulysses local-attention case).
    - ``"partitioned"`` — multi-device mesh under the automatic
      partitioner: route through the custom_partitioning wrappers
      (``ops/pallas/_partition.py``) so the kernel runs per shard. This
      is what the reference gets from launching its fused CUDA kernels
      per device under ``framework/parallel_executor.cc:504``.
    """
    if not (on_tpu() or _FORCE_DISPATCH):
        return "off"
    any_manual, all_manual = _manual_axes()
    if any_manual:
        return "raw" if all_manual else "off"
    return "raw" if single_device() else "partitioned"


def interpret() -> bool:
    """Whether pallas_call should run in interpreter mode."""
    return _FORCE_INTERPRET or not on_tpu()


def compiler_params(**kwargs):
    """TPU compiler params, or None off-TPU/interpret (ignored there)."""
    if interpret():
        return None
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)


class force_interpret:
    """Context manager: run all paddle_tpu Pallas kernels interpreted."""

    def __enter__(self):
        global _FORCE_INTERPRET
        self._prev = _FORCE_INTERPRET
        _FORCE_INTERPRET = True

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._prev
        return False


class force_dispatch:
    """Context manager: dispatch the kernel set even off-TPU (interpreted)
    — used by the virtual-mesh tests and the multichip dryrun to exercise
    the partitioned kernel path on CPU devices. Compilation of the jitted
    caller must happen inside the context (the interpret flag is read at
    lowering time)."""

    def __enter__(self):
        global _FORCE_DISPATCH, _FORCE_INTERPRET
        self._prev = (_FORCE_DISPATCH, _FORCE_INTERPRET)
        _FORCE_DISPATCH = True
        if not on_tpu():
            _FORCE_INTERPRET = True
        return self

    def __exit__(self, *exc):
        global _FORCE_DISPATCH, _FORCE_INTERPRET
        _FORCE_DISPATCH, _FORCE_INTERPRET = self._prev
        return False
