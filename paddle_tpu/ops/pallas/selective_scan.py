"""Fused selective-scan (Mamba SSM recurrence) — Pallas TPU kernel.

The recurrence ``h_t = exp(Δ_t A) ⊙ h_{t-1} + (Δ_t u_t) B_t``,
``y_t = ⟨h_t, C_t⟩ + D u_t`` is the hot loop of the Mamba family
(BASELINE.json north-star "Mamba-2 selective-scan"). The XLA formulation
(``models/mamba.py::selective_scan``, the numerical spec this kernel must
match) is scan-bound: the associative scan materializes [B, T, Ei, N]
discretized operands in HBM and makes log(T) passes over them.

Kernel design: grid (B, Ei/128, T/k) with the chunk dimension sequential
("arbitrary") and the running state h [N, 128] carried in VMEM scratch
across chunk steps. Per chunk the discretization (dA = exp(Δ·A),
dBu = Δu·B — [k, N, 128] tiles, state on sublanes, channels on lanes) is
vectorized VPU work; only the length-k FMA chain is sequential
(``fori_loop``, unrolled). HBM traffic is one read of u/Δ/B/C and one
write of y per token — no [B, T, Ei, N] intermediate ever exists.

Backward: the forward saves only the chunk-boundary states
([B, T/k, N, Ei] — a T/k-fold smaller residual than the full state
trajectory); the backward grid walks chunks in reverse, recomputes the
within-chunk states from the saved boundary state, runs the adjoint
recurrence ``g_t = dy_t C_t + exp(Δ_{t+1} A) g_{t+1}`` with the carry in
scratch, and accumulates the cross-chunk dA reduction in scratch,
writing per-batch partials summed outside.

Reference analogue: the role of Mamba's fused CUDA selective_scan —
structured like the reference's fused-op pattern
(``paddle/fluid/operators/fused/fused_embedding_eltwise_layernorm_op.cu``),
state kept on-chip for the whole sequential dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_LANES = 128
_DEF_CHUNK = 128


def _chunk(T: int, chunk: int | None) -> int:
    k = min(chunk or _DEF_CHUNK, T)
    return k


def supported(u, delta, A, B, C, D, chunk: int | None = None) -> bool:
    """Shape gate: channels lane-tiled, state sublane-aligned and small
    enough for the [k, N, 128] VMEM working set."""
    if u.ndim != 3 or A.ndim != 2 or B.ndim != 3:
        return False
    Bsz, T, Ei = u.shape
    N = A.shape[1]
    if A.shape[0] != Ei or B.shape != (Bsz, T, N) or C.shape != B.shape:
        return False
    if delta.shape != u.shape or D.shape != (Ei,):
        return False
    if Ei % _LANES:
        return False
    if N % 8 or N > 32:
        return False
    k = _chunk(T, chunk)
    if T % k or k % 8:
        return False
    return all(jnp.dtype(x.dtype) == jnp.float32
               for x in (u, delta, A, B, C, D))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(u_ref, dt_ref, at_ref, b_ref, c_ref, d_ref,
                y_ref, h0_ref, h_ref, *, k, n, nc):
    it = pl.program_id(2)

    @pl.when(it == 0)
    def _init():
        h_ref[:] = jnp.zeros_like(h_ref)

    # boundary state entering this chunk (the backward's restart point)
    h0_ref[0, 0] = h_ref[:]

    u = u_ref[0]                                   # [k, 128]
    dt = dt_ref[0]                                 # [k, 128]
    at = at_ref[:]                                 # [N, 128] (= A.T block)
    bc = b_ref[0]                                  # [k, N]
    cc = c_ref[0]                                  # [k, N]

    dA = jnp.exp(dt[:, None, :] * at[None])        # [k, N, 128]
    dBu = (dt * u)[:, None, :] * bc[..., None]     # [k, N, 128]

    # static Python loop: Mosaic TC has no dynamic_slice, and the fully
    # unrolled FMA chain is exactly the schedule we want anyway
    h = h_ref[:]
    hs_list = []
    for i in range(k):
        h = dA[i] * h + dBu[i]
        hs_list.append(h)
    hs = jnp.stack(hs_list)
    h_ref[:] = h

    y = jnp.sum(hs * cc[..., None], axis=1)        # [k, 128]
    y_ref[0] = y + u * d_ref[0]


def _fwd_call(u, delta, At, B, C, D2, k):
    Bsz, T, Ei = u.shape
    N = At.shape[0]
    nc, ne = T // k, Ei // _LANES
    grid = (Bsz, ne, nc)

    ue_spec = pl.BlockSpec((1, k, _LANES), lambda b, e, t: (b, t, e))
    bn_spec = pl.BlockSpec((1, k, N), lambda b, e, t: (b, t, 0))
    y, h0 = pl.pallas_call(
        functools.partial(_fwd_kernel, k=k, n=N, nc=nc),
        grid=grid,
        in_specs=[
            ue_spec,                                            # u
            ue_spec,                                            # delta
            pl.BlockSpec((N, _LANES), lambda b, e, t: (0, e)),  # A.T
            bn_spec,                                            # B
            bn_spec,                                            # C
            pl.BlockSpec((1, _LANES), lambda b, e, t: (0, e)),  # D
        ],
        out_specs=[
            ue_spec,                                            # y
            pl.BlockSpec((1, 1, N, _LANES),
                         lambda b, e, t: (b, t, 0, e)),         # h0/chunk
        ],
        out_shape=[
            jax.ShapeDtypeStruct(u.shape, jnp.float32),
            jax.ShapeDtypeStruct((Bsz, nc, N, Ei), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((N, _LANES), jnp.float32)],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(u, delta, At, B, C, D2)
    return y, h0


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_kernel(u_ref, dt_ref, at_ref, b_ref, c_ref, h0_ref, dy_ref,
                du_ref, ddt_ref, db_ref, dc_ref, dA_ref,
                m_ref, acc_ref, *, k, n, nc):
    it = pl.program_id(2)      # reversed chunk order via the index maps

    @pl.when(it == 0)
    def _init():
        m_ref[:] = jnp.zeros_like(m_ref)      # dA_{t+1}·g_{t+1} message
        acc_ref[:] = jnp.zeros_like(acc_ref)  # ΣA-grad accumulator

    u = u_ref[0]
    dt = dt_ref[0]
    at = at_ref[:]
    bc = b_ref[0]
    cc = c_ref[0]
    dy = dy_ref[0]
    h0 = h0_ref[0, 0]                              # [N, 128]

    dA = jnp.exp(dt[:, None, :] * at[None])        # [k, N, 128]
    dBu = (dt * u)[:, None, :] * bc[..., None]

    # recompute the within-chunk state trajectory from the boundary state
    h = h0
    hp_list = []
    for i in range(k):
        h = dA[i] * h + dBu[i]
        hp_list.append(h)
    hpost = jnp.stack(hp_list)
    # state entering step t: hprev[0] = h0, hprev[t] = hpost[t-1]
    hprev = jnp.concatenate([h0[None], hpost[:-1]], axis=0)

    # reverse adjoint: g_t = dy_t·C_t + m ;  m ← dA_t · g_t
    m = m_ref[:]
    gs_list = [None] * k
    for i in range(k - 1, -1, -1):
        g = cc[i][:, None] * dy[i][None, :] + m
        gs_list[i] = g
        m = dA[i] * g
    gs = jnp.stack(gs_list)
    m_ref[:] = m

    s1 = jnp.sum(gs * bc[..., None], axis=1)       # Σ_n g·B   [k, 128]
    du_ref[0] = dt * s1
    gdh = gs * dA * hprev                          # [k, N, 128]
    ddt_ref[0] = jnp.sum(gdh * at[None], axis=1) + u * s1
    # dB/dC reduce over *all* channels but this cell only sees one lane
    # block — write per-block partials (summed over the ne dim outside;
    # output accumulation across the e grid dim would need contiguous
    # revisiting, which the (b, e, t) grid order does not give)
    db_ref[0, 0] = jnp.sum(gs * (dt * u)[:, None, :], axis=2)   # [k, N]
    dc_ref[0, 0] = jnp.sum(hpost * dy[:, None, :], axis=2)      # [k, N]
    acc_ref[:] += jnp.sum(gdh * dt[:, None, :], axis=0)      # [N, 128]

    @pl.when(it == nc - 1)
    def _finish():
        dA_ref[0] = acc_ref[:]


def _bwd_call(u, delta, At, B, C, h0, dy, k):
    Bsz, T, Ei = u.shape
    N = At.shape[0]
    nc, ne = T // k, Ei // _LANES
    grid = (Bsz, ne, nc)

    # chunk dim walked in reverse
    ue_rev = pl.BlockSpec((1, k, _LANES),
                          lambda b, e, t, nc=nc: (b, nc - 1 - t, e))
    bn_rev = pl.BlockSpec((1, k, N), lambda b, e, t, nc=nc: (b, nc - 1 - t, 0))
    in_specs = [
        ue_rev,                                             # u
        ue_rev,                                             # delta
        pl.BlockSpec((N, _LANES), lambda b, e, t: (0, e)),  # A.T
        bn_rev,                                             # B
        bn_rev,                                             # C
        pl.BlockSpec((1, 1, N, _LANES),
                     lambda b, e, t, nc=nc: (b, nc - 1 - t, 0, e)),
        ue_rev,                                             # dy
    ]
    bn_part = pl.BlockSpec((1, 1, k, N),
                           lambda b, e, t, nc=nc: (b, e, nc - 1 - t, 0))
    du, ddt, dB_blocks, dC_blocks, dA_part = pl.pallas_call(
        functools.partial(_bwd_kernel, k=k, n=N, nc=nc),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            ue_rev,                                             # du
            ue_rev,                                             # ddelta
            bn_part,                                            # dB/e-block
            bn_part,                                            # dC/e-block
            pl.BlockSpec((1, N, _LANES), lambda b, e, t: (b, 0, e)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(u.shape, jnp.float32),
            jax.ShapeDtypeStruct(u.shape, jnp.float32),
            jax.ShapeDtypeStruct((Bsz, ne, T, N), jnp.float32),
            jax.ShapeDtypeStruct((Bsz, ne, T, N), jnp.float32),
            jax.ShapeDtypeStruct((Bsz, N, Ei), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, _LANES), jnp.float32),
            pltpu.VMEM((N, _LANES), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(u, delta, At, B, C, h0, dy)
    # reduce the per-lane-block partials over the channel-block dim
    return du, ddt, jnp.sum(dB_blocks, axis=1), jnp.sum(dC_blocks, axis=1), \
        dA_part


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------

def _fwd_dispatch(u, delta, At, B, C, D2, k, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.selective_scan_fwd(k)(u, delta, At, B, C, D2)
    return _fwd_call(u, delta, At, B, C, D2, k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _scan(k, part, u, delta, At, B, C, D2):
    y, _ = _fwd_dispatch(u, delta, At, B, C, D2, k, part)
    return y


def _scan_fwd(k, part, u, delta, At, B, C, D2):
    y, h0 = _fwd_dispatch(u, delta, At, B, C, D2, k, part)
    return y, (u, delta, At, B, C, D2, h0)


def _scan_bwd(k, part, res, dy):
    u, delta, At, B, C, D2, h0 = res
    if part:
        from paddle_tpu.ops.pallas import _partition
        du, ddt, dB, dC, dA_part = _partition.selective_scan_bwd(k)(
            u, delta, At, B, C, h0, dy)
    else:
        du, ddt, dB, dC, dA_part = _bwd_call(u, delta, At, B, C, h0, dy, k)
    # y += u·D terms and the cross-batch reductions stay outside: XLA
    # fuses them into the surrounding elementwise graph
    du = du + dy * D2[0]
    dAt = jnp.sum(dA_part, axis=0)                 # [N, Ei]
    dD = jnp.sum(dy * u, axis=(0, 1))              # [Ei]
    return du, ddt, dAt, dB, dC, dD[None]


_scan.defvjp(_scan_fwd, _scan_bwd)


def selective_scan(u, delta, A, B, C, D, chunk: int | None = None, *,
                   partitioned: bool = False):
    """Fused selective scan; same contract as
    ``models.mamba.selective_scan`` (u:[B,T,Ei] Δ:[B,T,Ei] A:[Ei,N]
    B,C:[B,T,N] D:[Ei] → y:[B,T,Ei]). ``supported(...)`` must hold.
    ``partitioned`` routes through custom_partitioning (batch/channel
    shardable; time sequential, replicated)."""
    k = _chunk(u.shape[1], chunk)
    y = _scan(k, bool(partitioned), u.astype(jnp.float32),
              delta.astype(jnp.float32),
              jnp.transpose(A).astype(jnp.float32),
              B.astype(jnp.float32), C.astype(jnp.float32),
              D.astype(jnp.float32)[None])
    return y
