"""Fused rotary position embedding (RoPE) application.

The rotation is pure VPU work; fusing it keeps q/k in VMEM for one pass
instead of the split/concat traffic of the jnp path. North-star item
(BASELINE.json: "rope"); no reference CUDA equivalent exists (the
reference predates RoPE models) — numerics match
``nn.functional.apply_rotary``.

Layout: x [B, T, H, D], cos/sin [T, D/2]. Backward rotates by the
negative angle (same kernel, sign flag); cos/sin receive zero gradients
(they are tables derived from integer positions).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import _support

_BLOCK_T = 256


def supported(x, cos, sin) -> bool:
    if x.ndim != 4 or cos.ndim != 2:
        return False
    B, T, H, D = x.shape
    if D % 2 or cos.shape != (T, D // 2) or sin.shape != cos.shape:
        return False
    bt = min(_BLOCK_T, T)
    if T % bt or bt % 8:
        return False
    return x.dtype in (jnp.float32, jnp.bfloat16)


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, d2, sign):
    x1 = x_ref[0, 0, :, :d2].astype(jnp.float32)
    x2 = x_ref[0, 0, :, d2:].astype(jnp.float32)
    cos = cos_ref[...]
    sin = sin_ref[...] * sign
    o_ref[0, 0, :, :d2] = (x1 * cos - x2 * sin).astype(o_ref.dtype)
    o_ref[0, 0, :, d2:] = (x2 * cos + x1 * sin).astype(o_ref.dtype)


def _rope_call(x, cos, sin, sign):
    B, T, H, D = x.shape
    d2 = D // 2
    bt = min(_BLOCK_T, T)
    xt = jnp.transpose(x, (0, 2, 1, 3))  # [B, H, T, D]: Mosaic-tileable
    ot = pl.pallas_call(
        functools.partial(_rope_kernel, d2=d2, sign=sign),
        grid=(B, H, T // bt),
        in_specs=[
            pl.BlockSpec((1, 1, bt, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((bt, d2), lambda b, h, i: (i, 0)),
            pl.BlockSpec((bt, d2), lambda b, h, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bt, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(xt.shape, x.dtype),
        interpret=_support.interpret(),
    )(xt, cos.astype(jnp.float32), sin.astype(jnp.float32))
    return jnp.transpose(ot, (0, 2, 1, 3))


def _rope_dispatch(x, cos, sin, sign, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.rope(sign)(x, cos, sin)
    return _rope_call(x, cos, sin, sign)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _rope(part, x, cos, sin):
    return _rope_dispatch(x, cos, sin, 1.0, part)


def _rope_fwd(part, x, cos, sin):
    return _rope_dispatch(x, cos, sin, 1.0, part), (cos, sin)


def _rope_bwd(part, res, g):
    cos, sin = res
    dx = _rope_dispatch(g, cos, sin, -1.0, part)
    return dx, jnp.zeros_like(cos), jnp.zeros_like(sin)


_rope.defvjp(_rope_fwd, _rope_bwd)


def apply_rotary(x, cos, sin, *, partitioned: bool = False):
    """Fused RoPE for [B, T, H, D] x with [T, D/2] cos/sin tables.
    ``partitioned`` routes through custom_partitioning (batch/seq/head
    shardable; the tables shard with the sequence)."""
    return _rope(bool(partitioned), x, cos, sin)
