"""Pallas TPU kernels for the hot-op set (reference: CUDA kernels under
``paddle/fluid/operators/fused/``, ``operators/math/``,
``operators/optimizers/``).

- ``flash_attention`` — fused attention, never materializes [T, T]
  (ref ``fused/multihead_matmul_op.cu``)
- ``rms_norm`` / ``layer_norm`` — fused row norms with saved statistics
  (ref ``layer_norm_op.cu``, ``fused/skip_layernorm_op.cu``)
- ``softmax_cross_entropy`` — fused [N, V] loss, probs never stored
  (ref ``softmax_with_cross_entropy_op.cu``, ``math/softmax.cu``)
- ``fused_linear_cross_entropy`` — LM-head matmul ⊗ xent, the [N, V]
  logits never stored (ref fuses only softmax+xent; this also folds the
  preceding FC — the memory lever at real vocab sizes)
- ``apply_rotary`` — fused RoPE rotation
- ``adamw_update`` — fused optimizer update (ref ``optimizers/adam_op.cu``)

All kernels run compiled on TPU and interpreted elsewhere
(``_support.interpret()``); all are differentiable via ``jax.custom_vjp``.
"""

from paddle_tpu.ops.pallas import _support
from paddle_tpu.ops.pallas import flash_attention as _fa
from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.norm import layer_norm, rms_norm
from paddle_tpu.ops.pallas.rope import apply_rotary
from paddle_tpu.ops.pallas.softmax_xent import softmax_cross_entropy
from paddle_tpu.ops.pallas.linear_xent import (
    chunked_linear_cross_entropy, fused_linear_cross_entropy,
)
from paddle_tpu.ops.pallas.adamw import adamw_update
from paddle_tpu.ops.pallas.selective_scan import (
    selective_scan, supported as selective_scan_supported,
)

force_interpret = _support.force_interpret
force_dispatch = _support.force_dispatch
on_tpu = _support.on_tpu
dispatch_mode = _support.dispatch_mode


def partition_stats() -> dict:
    """Lowering decisions taken by the multi-chip (custom_partitioning)
    kernel wrappers, keyed ``<unit>:<kernel|fallback>`` — recorded in the
    multichip driver artifact as proof the Pallas path executed under
    sharding."""
    from paddle_tpu.ops.pallas import _partition
    return dict(_partition.stats)


def reset_partition_stats() -> None:
    from paddle_tpu.ops.pallas import _partition
    _partition.reset_stats()


__all__ = [
    "flash_attention", "flash_attention_supported", "rms_norm", "layer_norm",
    "softmax_cross_entropy", "fused_linear_cross_entropy",
    "chunked_linear_cross_entropy", "apply_rotary", "adamw_update",
    "selective_scan", "selective_scan_supported",
    "force_interpret", "force_dispatch", "on_tpu", "dispatch_mode",
    "partition_stats", "reset_partition_stats",
]


def flash_attention_supported(q, k, v, *, causal=False) -> bool:
    return _fa.supported(q, k, v, causal=causal)
