"""Fused AdamW parameter update.

Reference CUDA equivalent: ``paddle/fluid/operators/optimizers/
adam_op.cu`` (one kernel updating param + both moments in place). Here
one Pallas kernel reads (p, m, v, g) once and writes (p, m, v) —
4 reads + 3 writes of HBM traffic per element, with
``input_output_aliases`` donating the buffers. Scalars (lr, betas, eps,
weight decay, bias corrections) arrive via SMEM so one compiled kernel
serves every step of a schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_LANES = 128
_BLOCK_ROWS = 512


def _adamw_kernel(sc_ref, p_ref, m_ref, v_ref, g_ref,
                  po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd, c1, c2 = (sc_ref[i] for i in range(7))
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    p = p_ref[...].astype(jnp.float32)
    update = (m * c1) / (jnp.sqrt(v * c2) + eps)
    p = p - lr * (update + wd * p)
    po_ref[...] = p.astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def adamw_update(p, m, v, g, *, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                 weight_decay=0.01, step):
    """One fused AdamW step on a single tensor. Returns (p, m, v).

    ``m``/``v`` must be float32; ``step`` is the 1-based step count used
    for bias correction. Scalars may be traced (schedules jit cleanly).
    """
    shape, dtype = p.shape, p.dtype
    n = p.size
    cols = _LANES
    rows = -(-n // cols)
    pad = rows * cols - n

    def to2d(x, dt):
        flat = x.reshape(-1).astype(dt)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), dt)])
        return flat.reshape(rows, cols)

    step_f = jnp.asarray(step, jnp.float32)
    c1 = 1.0 / (1.0 - jnp.asarray(beta1, jnp.float32) ** step_f)
    c2 = 1.0 / (1.0 - jnp.asarray(beta2, jnp.float32) ** step_f)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32), c1, c2])

    br = min(_BLOCK_ROWS, rows)
    nrb = -(-rows // br)
    # gradients go in as float32: quantizing an fp32 master grad to a bf16
    # param dtype would discard mantissa the kernel immediately needs
    p2, m2, v2, g2 = (to2d(p, dtype), to2d(m, jnp.float32),
                      to2d(v, jnp.float32), to2d(g, jnp.float32))
    po, mo, vo = pl.pallas_call(
        _adamw_kernel,
        grid=(nrb,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
            pl.BlockSpec((br, cols), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), dtype),
            jax.ShapeDtypeStruct((rows, cols), jnp.float32),
            jax.ShapeDtypeStruct((rows, cols), jnp.float32),
        ],
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=_support.interpret(),
    )(scalars, p2, m2, v2, g2)

    def un2d(x, dt):
        flat = x.reshape(-1)
        if pad:
            flat = flat[:n]
        return flat.reshape(shape).astype(dt)

    return un2d(po, dtype), un2d(mo, jnp.float32), un2d(vo, jnp.float32)
