"""Fused single-token decode attention over the static KV cache.

The serving hot loop: every generated token attends its one query
against the filled prefix of the per-layer cache. The XLA einsum path
pays three taxes this kernel deletes (all measured on the v5e bench
geometry, BASELINE.md decode table):

- the per-layer ``lax.scan`` slice of the stacked cache materializes a
  full layer copy per layer per step (XLA cannot fuse a dynamic-slice
  producer into a custom call — measured 1.45 ms/step of pure copy on
  the bench geometry). This kernel takes the WHOLE stacked
  [L, B, Hkv, S, D] buffers and selects the layer in its index maps via
  a scalar-prefetched layer id — no slice ever exists;
- it reads the whole [S] buffer even when only ``index`` of ``S``
  positions are live — the index maps clamp the block id to the filled
  prefix (blocks past the fill repeat the previous block index and
  Mosaic elides the repeated DMA);
- the int8 cache dequant materializes full bf16 copies of k/v — here
  the int8 blocks go MXU-ready as ``convert(int8)`` and both scales fold
  into the [G, bk] logit/prob planes (column-wise multiplies), so the
  HBM traffic really is the int8 bytes.

The fresh token's k/v (raw dtype, exact) join the softmax as grid step
0; cache blocks stream as steps 1..nk with positions ``>= index``
masked. Layout contract matches ``models._common.init_kv_cache``
(stacked [L, B, Hkv, S, D], f32 scales [L, B, Hkv, S] for int8);
q [B, 1, Hq, D].

Reference role: the decode half of the reference's fused attention
serving path (``paddle/fluid/operators/fused/multihead_matmul_op.cu``
feeding ``inference/api/analysis_predictor.h``); inference-only, no VJP.

Batching: the GenerationEngine's fused decode step invokes this kernel
under ``jax.vmap`` (one mapped axis per engine slot, per-slot caches
and fill positions). jax's pallas batching rule lowers that by growing
the grid, and ``tests/test_decode_attention.py`` pins the behavior
(vmapped output bit-equal to per-slot calls, interpret mode) along with
the off-TPU einsum fallback arm — the engine's dispatch is explicit,
not incidental.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

LANES = 128
NEG_INF = -1e30


def _block_k(S: int) -> int:
    for bk in (512, 256, 128):
        if S % bk == 0:
            return bk
    return 0


def supported(q, cache) -> bool:
    """Kernel gate; callers fall back to the einsum path when False.
    Decode chunks only (T == 1); prefill always takes the flash path.
    ``cache`` holds the STACKED buffers ([L, B, Hkv, S, D]). Under a
    multi-device mesh the custom_partitioning wrapper
    (``_partition.decode_attn``) runs the kernel per batch/head shard —
    TP-sharded serving keeps the kernel path (tp must divide
    num_kv_heads, the same constraint correct Megatron attention
    sharding already imposes; a larger tp fails inside jax's sharding
    conversion before any fallback can intercept)."""
    mode = _support.dispatch_mode()
    if mode not in ("raw", "partitioned"):
        return False
    if q.ndim != 4 or q.shape[1] != 1:
        return False
    B, T, Hq, D = q.shape
    k = cache[0]
    if k.ndim != 5:
        return False
    _, _, Hkv, S, Dk = k.shape
    if Dk != D or D not in (64, 128, 256) or Hq % Hkv:
        return False
    if _block_k(S) == 0:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    quantized = len(cache) == 4
    if quantized and k.dtype != jnp.int8:
        return False
    if not quantized and k.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    return True


def _kernel(sp_ref, q_ref, kn_ref, vn_ref, kc_ref, vc_ref, *rest,
            scale, bk, nk, G, Hkv, quantized, out_dtype):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    j = pl.program_id(1)
    idx = sp_ref[1]

    @pl.when(j == 0)
    def _fresh():
        # the chunk's own token: p = exp(s - m) = 1, l = 1, acc = v_new
        q = q_ref[0].astype(jnp.float32)            # [Hq, D]
        kn = kn_ref[0].astype(jnp.float32)          # [Hkv, D]
        vn = vn_ref[0].astype(jnp.float32)
        for h in range(Hkv):
            rows = slice(h * G, (h + 1) * G)
            s_h = jnp.sum(q[rows] * kn[h:h + 1], axis=1,
                          keepdims=True) * scale    # [G, 1]
            m_ref[rows, :] = jnp.broadcast_to(s_h, (G, LANES))
            acc_ref[rows, :] = jnp.broadcast_to(vn[h:h + 1],
                                                (G, vn.shape[1]))
        l_ref[:, :] = jnp.ones_like(l_ref)

    last_block = jnp.maximum(idx - 1, 0) // bk

    @pl.when((j > 0) & (j - 1 <= last_block))
    def _cache_block():
        jb = j - 1
        # ONE block-diagonal dot for ALL heads instead of Hkv unrolled
        # [G, D]×[D, bk] matvecs: q [Hq, D] against the whole block
        # [Hkv·bk, D] computes every cross-head product and the
        # block-diagonal mask kills the wrong-head logits (exp(NEG) = 0,
        # so the p·V dot's cross-head sums vanish exactly). The waste
        # FLOPs are Hkv× the useful ones — irrelevant next to HBM (the
        # kernel is bandwidth-bound); the instruction-count drop is what
        # matters (the unrolled form measured ~56 µs per grid step,
        # ~16× its DMA bound, and scaled linearly with batch).
        # Operands stay in their stored dtype through the MXU (bf16, or
        # a bare int8 convert) with f32 accumulation.
        q = q_ref[0]                                # [Hq, D], model dtype
        Hq, D = q.shape
        cdt = q.dtype if kc_ref.dtype == jnp.int8 else kc_ref.dtype
        if q.dtype != cdt:
            q = q.astype(cdt)
        kb = kc_ref[0, 0]                           # [Hkv, bk, D]
        if kb.dtype != cdt:
            kb = kb.astype(cdt)
        kb = kb.reshape(Hkv * bk, D)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Hq, Hkv·bk]
        if quantized:
            # per-position scale folds into the logit plane (per column)
            s = s * ks_ref[0, 0].reshape(1, Hkv * bk)
        row_h = jax.lax.broadcasted_iota(
            jnp.int32, (Hq, Hkv * bk), 0) // G
        col = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv * bk), 1)
        pos = jb * bk + col % bk
        valid = (row_h == col // bk) & (pos < idx)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                      # [Hq, Hkv·bk]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        if quantized:
            # v scale folds into the prob plane
            p = p * vs_ref[0, 0].reshape(1, Hkv * bk)
        vb = vc_ref[0, 0]
        if vb.dtype != cdt:
            vb = vb.astype(cdt)
        pv = jax.lax.dot_general(
            p.astype(cdt), vb.reshape(Hkv * bk, D),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [Hq, D]
        acc_ref[:, :] = acc_ref[:, :] * alpha + pv

    @pl.when(j == nk)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:, :] / jnp.where(l == 0.0, 1.0, l)).astype(
            out_dtype)


def decode_attention(q, k_new, v_new, cache, layer, index, *, scale: float):
    """q [B, 1, Hq, D]; k_new/v_new [B, Hkv, 1, D] (this step's raw k/v);
    ``cache`` the STACKED read-only buffers ([L, B, Hkv, S, D], int8
    layout adds [L, B, Hkv, S] scales); ``layer`` this block's layer id
    (traced under the layer scan); ``index`` traced int32 fill position
    (the layer's cache holds tokens [0, index)). Returns [B, 1, Hq, D]."""
    B, T, Hq, D = q.shape
    Hkv = k_new.shape[1]
    G = Hq // Hkv
    quantized = len(cache) == 4

    q2 = q.reshape(B, Hq, D)
    kn2 = k_new.reshape(B, Hkv, D)
    vn2 = v_new.reshape(B, Hkv, D)
    sp = jnp.stack([jnp.asarray(layer, jnp.int32),
                    jnp.asarray(index, jnp.int32)])

    if _support.dispatch_mode() == "partitioned":
        from paddle_tpu.ops.pallas import _partition
        out = _partition.decode_attn(float(scale), G, quantized)(
            sp, q2, kn2, vn2, *cache)
    else:
        out = raw_call(sp, q2, kn2, vn2, *cache, scale=scale)
    return out.reshape(B, 1, Hq, D)


def raw_call(sp, q2, kn2, vn2, *cache, scale: float):
    """The pallas_call on (per-shard) local shapes: sp = int32[2]
    (layer, index); q2 [B, Hq, D]; kn2/vn2 [B, Hkv, D]; cache the
    stacked buffers. Returns [B, Hq, D]."""
    B, Hq, D = q2.shape
    Hkv = kn2.shape[1]
    G = Hq // Hkv
    quantized = len(cache) == 4
    kc, vc = cache[0], cache[1]
    S = kc.shape[3]
    bk = _block_k(S)
    nk = S // bk

    def cache_map(b, j, sp_ref):
        last = jnp.maximum(sp_ref[1] - 1, 0) // bk
        return (sp_ref[0], b, 0,
                jnp.minimum(jnp.maximum(j - 1, 0), last), 0)

    def scale_map(b, j, sp_ref):
        last = jnp.maximum(sp_ref[1] - 1, 0) // bk
        return (sp_ref[0], b, 0, jnp.minimum(jnp.maximum(j - 1, 0), last))

    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, 1, Hkv, bk, D), cache_map),
        pl.BlockSpec((1, 1, Hkv, bk, D), cache_map),
    ]
    args = [q2, kn2, vn2, kc, vc]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, Hkv, bk), scale_map),
                     pl.BlockSpec((1, 1, Hkv, bk), scale_map)]
        args += [cache[2], cache[3]]

    kernel = functools.partial(
        _kernel, scale=scale, bk=bk, nk=nk, G=G, Hkv=Hkv,
        quantized=quantized, out_dtype=q2.dtype)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, nk + 1),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, Hq, D), lambda b, j, s: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hq, D), jnp.float32),
                pltpu.VMEM((Hq, LANES), jnp.float32),
                pltpu.VMEM((Hq, LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q2.dtype),
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(sp, *args)
