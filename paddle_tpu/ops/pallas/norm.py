"""Fused row-normalization kernels (RMSNorm / LayerNorm).

Reference CUDA equivalents: ``paddle/fluid/operators/layer_norm_op.cu``
(Welford row statistics) and ``fused/skip_layernorm_op.cu``. One VMEM
pass per row block computes statistics + normalized output; the row
statistics (rstd, and mean for LayerNorm) are saved for the backward
pass, which fuses dx with the dw/db cross-row reductions (dw/db
accumulate into a revisited output block across the sequential grid).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_BLOCK_ROWS = 256
_LANES = 128


def _shape2d(x):
    h = x.shape[-1]
    n = x.size // h
    return n, h


def supported(x, weight, bias=None) -> bool:
    n, h = _shape2d(x)
    if h % 128 or h > 16384:
        return False
    br = min(_BLOCK_ROWS, n)
    if n % br or br % 8:
        return False
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if weight is not None and weight.shape != (h,):
        return False
    return bias is None or (bias.shape == (h,) and (
        weight is None or bias.dtype == weight.dtype))


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = x * rstd
    y_ref[...] = (xhat * w_ref[...].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dw_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    rstd = rstd_ref[:, :1]
    xhat = x * rstd
    wg = g * w
    c = jnp.mean(wg * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (wg - xhat * c)).astype(dx_ref.dtype)
    dw_ref[...] += jnp.sum(g * xhat, axis=0)


def _rms_fwd(x2d, w, eps):
    n, h = x2d.shape
    br = min(_BLOCK_ROWS, n)
    nb = n // br
    y, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        interpret=_support.interpret(),
    )(x2d, w)
    return y, rstd


def _rms_bwd_call(x2d, w, rstd, g):
    n, h = x2d.shape
    br = min(_BLOCK_ROWS, n)
    nb = n // br
    dx, dw = pl.pallas_call(
        _rms_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            jax.ShapeDtypeStruct((h,), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=_support.interpret(),
    )(x2d, w, rstd, g)
    return dx, dw


def _rms_fwd_dispatch(x2d, w, eps, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.rms_fwd(eps)(x2d, w)
    return _rms_fwd(x2d, w, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _rms(eps, part, x2d, w):
    y, _ = _rms_fwd_dispatch(x2d, w, eps, part)
    return y


def _rms_vjp_fwd(eps, part, x2d, w):
    y, rstd = _rms_fwd_dispatch(x2d, w, eps, part)
    return y, (x2d, w, rstd)


def _rms_vjp_bwd(eps, part, res, g):
    x2d, w, rstd = res
    if part:
        from paddle_tpu.ops.pallas import _partition
        dx, dw = _partition.rms_bwd(eps)(x2d, w, rstd, g)
    else:
        dx, dw = _rms_bwd_call(x2d, w, rstd, g)
    return dx, dw.astype(w.dtype)


_rms.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


def rms_norm(x, weight, epsilon: float = 1e-6, *, partitioned: bool = False):
    """Fused RMSNorm over the last axis. ``supported(x, weight)`` must
    hold. Matches ``nn.functional.rms_norm`` numerics (fp32 statistics).
    ``partitioned`` routes through custom_partitioning so the kernel runs
    per-shard under a multi-device mesh."""
    n, h = _shape2d(x)
    w = weight if weight is not None else jnp.ones((h,), x.dtype)
    y = _rms(float(epsilon), bool(partitioned), x.reshape(n, h), w)
    return y.reshape(x.shape)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    w = w_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    y_ref[...] = (xhat * w + b).astype(y_ref.dtype)
    mean_ref[...] = jnp.broadcast_to(mean, mean_ref.shape)
    rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _ln_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dw_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    mean = mean_ref[:, :1]
    rstd = rstd_ref[:, :1]
    xhat = (x - mean) * rstd
    wg = g * w
    c1 = jnp.mean(wg, axis=1, keepdims=True)
    c2 = jnp.mean(wg * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (wg - c1 - xhat * c2)).astype(dx_ref.dtype)
    dw_ref[...] += jnp.sum(g * xhat, axis=0)
    db_ref[...] += jnp.sum(g, axis=0)


def _ln_fwd(x2d, w, b, eps):
    n, h = x2d.shape
    br = min(_BLOCK_ROWS, n)
    nb = n // br
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        interpret=_support.interpret(),
    )(x2d, w, b)


def _ln_fwd_dispatch(x2d, w, b, eps, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.ln_fwd(eps)(x2d, w, b)
    return _ln_fwd(x2d, w, b, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ln(eps, b_dtype, part, x2d, w, b):
    y, _, _ = _ln_fwd_dispatch(x2d, w, b, eps, part)
    return y


def _ln_vjp_fwd(eps, b_dtype, part, x2d, w, b):
    y, mean, rstd = _ln_fwd_dispatch(x2d, w, b, eps, part)
    return y, (x2d, w, mean, rstd)


def _ln_bwd_call(x2d, w, mean, rstd, g):
    n, h = x2d.shape
    br = min(_BLOCK_ROWS, n)
    nb = n // br
    dx, dw, db = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            jax.ShapeDtypeStruct((h,), jnp.float32),
            jax.ShapeDtypeStruct((h,), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=_support.interpret(),
    )(x2d, w, mean, rstd, g)
    return dx, dw, db


def _ln_vjp_bwd(eps, b_dtype, part, res, g):
    x2d, w, mean, rstd = res
    if part:
        from paddle_tpu.ops.pallas import _partition
        dx, dw, db = _partition.ln_bwd(eps)(x2d, w, mean, rstd, g)
    else:
        dx, dw, db = _ln_bwd_call(x2d, w, mean, rstd, g)
    return dx, dw.astype(w.dtype), db.astype(b_dtype)


_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def layer_norm(x, weight, bias, epsilon: float = 1e-5, *,
               partitioned: bool = False):
    """Fused LayerNorm over the last axis (``supported`` must hold).
    ``partitioned`` routes through custom_partitioning so the kernel runs
    per-shard under a multi-device mesh."""
    n, h = _shape2d(x)
    w = weight if weight is not None else jnp.ones((h,), x.dtype)
    b = bias if bias is not None else jnp.zeros((h,), x.dtype)
    y = _ln(float(epsilon), jnp.dtype(b.dtype).name, bool(partitioned),
            x.reshape(n, h), w, b)
    return y.reshape(x.shape)
