"""Flash attention for TPU — Pallas kernels, forward + backward.

Replaces the reference's fused attention CUDA path
(``paddle/fluid/operators/fused/multihead_matmul_op.cu``: cuBLAS batched
GEMM + softmax kernel, which materializes the [B, H, T, T] score matrix).
Here the online-softmax (flash) formulation streams K/V blocks through
VMEM so the score matrix never exists in HBM, q/k/v blocks feed the MXU
as [block, head_dim] tiles, and the [B,H,T] log-sum-exp is saved for the
backward pass (``jax.custom_vjp``).

The public entry takes the framework-wide [B, T, H, D] layout
(``paddle_tpu/nn/attention.py``) and transposes to [B, H, T, D] at the
kernel boundary (Mosaic requires the last two block dims to be the
tiled ones; XLA usually fuses the transpose into the producing
projection). Row statistics (lse, and the backward's delta) are stored
lane-replicated as [B, H, T, 128] — the Mosaic-aligned layout for
per-row scalars. Grouped-query attention maps q-head h to kv-head
``h // (Hq // Hkv)`` in the index maps; the backward pass computes
per-q-head dk/dv and sums over the group outside the kernel.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
LANES = 128
NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() finite


def _blocks(Tq: int, Tk: int, block_q, block_k):
    bq = min(block_q or DEFAULT_BLOCK_Q, Tq)
    bk = min(block_k or DEFAULT_BLOCK_K, Tk)
    return bq, bk


def supported(q, k, v, *, causal: bool = False, block_q=None,
              block_k=None) -> bool:
    """Shape/dtype gate for the kernel; callers fall back to the einsum
    path when False."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return False
    B, Tq, Hq, D = q.shape
    _, Tk, Hkv, Dk = k.shape
    if v.shape != k.shape or Dk != D:
        return False
    if Hq % Hkv != 0:
        return False
    if D not in (64, 128, 256):
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    bq, bk = _blocks(Tq, Tk, block_q, block_k)
    if Tq % bq or Tk % bk:
        return False
    if bq % 8 or bk % 128:  # sublane/lane alignment of the [bq, bk] tile
        return False
    return True


def _causal_mask(s, iq, ik, bq, bk, delta_qk):
    row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) \
        + iq * bq + delta_qk
    col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
    return jnp.where(col <= row, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, nk, delta_qk):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, delta_qk)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0, :, :],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv

    if causal:
        # skip blocks entirely above the diagonal
        @pl.when(ik * bk <= iq * bq + (bq - 1) + delta_qk)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)
        lse_ref[0, 0, :, :] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd(qt, kt, vt, causal, scale, block_q, block_k):
    """qt/kt/vt in [B, H, T, D]; returns (o [B,H,Tq,D], lse [B,H,Tq,128])."""
    B, Hq, Tq, D = qt.shape
    _, Hkv, Tk, _ = kt.shape
    bq, bk = _blocks(Tq, Tk, block_q, block_k)
    nq, nk = Tq // bq, Tk // bk
    group = Hq // Hkv
    grid = (B, Hq, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        delta_qk=Tk - Tq)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, g=group: (b, h // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, qt.dtype),
            jax.ShapeDtypeStruct((B, Hq, Tq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_support.interpret(),
    )(qt, kt, vt)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref, dq_ref,
               dq_acc, *, scale, causal, bq, bk, nk, delta_qk):
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, delta_qk)
        lse = lse_ref[0, 0, :, :1]               # (bq, 1)
        p = jnp.exp(s - lse)
        do = do_ref[0, 0, :, :]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0, :, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dta = dta_ref[0, 0, :, :1]               # rowsum(do * o)
        ds = p * (dp - dta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ik * bk <= iq * bq + (bq - 1) + delta_qk)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, bq, bk, nq, delta_qk):
    ik, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, delta_qk)
        lse = lse_ref[0, 0, :, :1]
        p = jnp.exp(s - lse)                     # (bq, bk)
        do = do_ref[0, 0, :, :]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0, 0, :, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dta = dta_ref[0, 0, :, :1]
        ds = p * (dp - dta) * scale              # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ik * bk <= iq * bq + (bq - 1) + delta_qk)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(qt, kt, vt, ot, lse, do_t, causal, scale, block_q, block_k):
    B, Hq, Tq, D = qt.shape
    _, Hkv, Tk, _ = kt.shape
    bq, bk = _blocks(Tq, Tk, block_q, block_k)
    nq, nk = Tq // bq, Tk // bk
    group = Hq // Hkv

    # delta_i = rowsum(dO_i * O_i), lane-replicated to [B, H, Tq, 128]
    dta = jnp.einsum("bhtd,bhtd->bht", do_t.astype(jnp.float32),
                     ot.astype(jnp.float32))
    dta = jnp.broadcast_to(dta[..., None], (B, Hq, Tq, LANES))

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, bk, D), lambda b, h, i, j, g=group: (b, h // g, j, 0))
    row_spec = pl.BlockSpec(
        (1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0))

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        delta_qk=Tk - Tq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, Hq, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, qt.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_support.interpret(),
    )(qt, kt, vt, do_t, lse, dta)

    # dkv grid order: (b, h, ik, iq) — q blocks innermost
    q_spec_t = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    kv_spec_t = pl.BlockSpec(
        (1, 1, bk, D), lambda b, h, j, i, g=group: (b, h // g, j, 0))
    row_spec_t = pl.BlockSpec(
        (1, 1, bq, LANES), lambda b, h, j, i: (b, h, i, 0))
    dkv_out_spec = pl.BlockSpec(
        (1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))

    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nq=nq,
        delta_qk=Tk - Tq)
    # per-q-head dk/dv ([B, Hq, Tk, D]); GQA groups are reduced below
    dk_q, dv_q = pl.pallas_call(
        dkv_kernel,
        grid=(B, Hq, nk, nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Tk, D), kt.dtype),
            jax.ShapeDtypeStruct((B, Hq, Tk, D), vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_support.interpret(),
    )(qt, kt, vt, do_t, lse, dta)

    if group > 1:
        dk = dk_q.reshape(B, Hkv, group, Tk, D).sum(axis=2).astype(kt.dtype)
        dv = dv_q.reshape(B, Hkv, group, Tk, D).sum(axis=2).astype(vt.dtype)
    else:
        dk, dv = dk_q, dv_q
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wiring (operates in [B, H, T, D])
# ---------------------------------------------------------------------------

def _fwd_dispatch(qt, kt, vt, causal, scale, block_q, block_k, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        group = qt.shape[1] // kt.shape[1]
        return _partition.flash_fwd(causal, scale, block_q, block_k,
                                    group)(qt, kt, vt)
    return _fwd(qt, kt, vt, causal, scale, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash(causal, scale, block_q, block_k, part, qt, kt, vt):
    o, _ = _fwd_dispatch(qt, kt, vt, causal, scale, block_q, block_k, part)
    return o


def _flash_fwd(causal, scale, block_q, block_k, part, qt, kt, vt):
    o, lse = _fwd_dispatch(qt, kt, vt, causal, scale, block_q, block_k, part)
    return o, (qt, kt, vt, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, part, res, do):
    qt, kt, vt, o, lse = res
    if part:
        from paddle_tpu.ops.pallas import _partition
        group = qt.shape[1] // kt.shape[1]
        return _partition.flash_bwd(causal, scale, block_q, block_k,
                                    group)(qt, kt, vt, o, lse, do)
    return _bwd_impl(qt, kt, vt, o, lse, do, causal, scale, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale=None,
                    block_q: int | None = None, block_k: int | None = None,
                    partitioned: bool = False):
    """Flash attention, [B, T, H, D] in/out. Differentiable (custom VJP).

    ``supported(q, k, v, causal=...)`` must hold; callers are expected to
    fall back to the dense path otherwise (``nn.functional.
    scaled_dot_product_attention`` does this automatically).
    ``partitioned`` routes both passes through custom_partitioning so the
    kernels run per-shard (batch/head sharded, sequence replicated) under
    a multi-device mesh.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    o = _flash(causal, float(scale), block_q, block_k, bool(partitioned),
               qt, kt, vt)
    return jnp.transpose(o, (0, 2, 1, 3))
