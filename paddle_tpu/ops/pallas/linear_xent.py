"""Fused LM-head ⊗ cross-entropy: loss(h @ W) with logits never stored.

Reference equivalent: ``paddle/fluid/operators/softmax_with_cross_entropy_op.cu``
fused with the preceding FC — the reference fuses softmax+xent at any
vocab size but still materializes the [N, V] logits the FC produced. At
real LM vocab (32k–50k) that tensor is the single largest activation in
the model (bench shape: 16384 × 32000 f32 = 2.1 GB forward + the same
again for dlogits in backward). This module fuses the hidden→vocab
matmul *into* the loss so neither ever exists in HBM:

- forward: grid (row blocks × vocab tiles). Each step computes one
  ``[bN, bV]`` logits tile on the MXU in VMEM (``h_blk @ W_tile``,
  f32 accumulation), folds it into an online max/log-sum-exp merge, and
  picks up the label logit by comparing an in-tile column iota against
  the (lane-replicated) labels. Outputs: lse [N] and the selected logit
  [N]; loss = lse − sel.
- backward dH: same grid; recomputes the tile, forms
  ``dlogits = (softmax − onehot)·g`` in registers, and accumulates
  ``dlogits @ W_tileᵀ`` into a VMEM [bN, E] scratch, emitted on the
  last vocab tile.
- backward dW: transposed grid (vocab outer, rows inner) so each
  ``[E, bV]`` output block stays resident in VMEM while all row blocks
  stream through, accumulating ``h_blkᵀ @ dlogits`` in f32 directly in
  the output ref.

Cost model: 10·N·E·V matmul FLOPs vs the unfused 6 (both backward
kernels recompute their logits tile), in exchange for O(N·V) → O(N)
loss-path HBM traffic and activation memory. At bench shapes the
lm-head is ~7% of model FLOPs, so the ~4% FLOP overhead buys back
gigabytes of HBM — the lever for larger batch/seq (BASELINE.md r3
sweep: bs12/16 and seq-4096 OOM with logits resident).

Alignment: E % 128 == 0, V divisible by one of the candidate vocab
tiles, rows divisible by the row block (callers pad rows or fall back).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_NEG_INF = -1e30
_LANES = 128

# Vocab-tile candidates, largest first. The tile must divide V exactly
# (no masking path — a partial tile would poison the running max) and
# the per-tile VMEM working set must fit ~16 MB with double-buffering.
_BV_CANDIDATES = (1024, 896, 768, 640, 512, 384, 256, 128)
# bytes of VMEM per vocab-tile column the kernel holds, by kernel kind:
# fwd/dh hold the W tile (itemsize, double-buffered); dw additionally
# holds its f32 accumulator output block (double-buffered by the
# pipeline) — measured: bv=640 @ E=2048 compiles for fwd/dh but blows
# VMEM for dw, bv=384 fits all three.
_BUDGET_FWD = 6 * 1024 * 1024
_BUDGET_DW = 10 * 1024 * 1024


def _pick_bv(e: int, v: int, itemsize: int, *, for_dw: bool = False):
    per_col = e * itemsize * 2 + (e * 4 * 2 if for_dw else 0)
    budget = _BUDGET_DW if for_dw else _BUDGET_FWD
    for bv in _BV_CANDIDATES:
        if v % bv == 0 and bv * per_col <= budget:
            return bv
    return None


def _pick_bn(n: int, e: int) -> int:
    bn = 256 if e <= 2048 else 128
    return min(bn, n)


def supported(hidden, weight, labels) -> bool:
    if hidden.ndim != 2 or weight.ndim != 2 or labels.ndim != 1:
        return False
    n, e = hidden.shape
    e2, v = weight.shape
    if e2 != e or labels.shape[0] != n:
        return False
    if e % _LANES or n < 8 or n % 8:
        return False
    bn = _pick_bn(n, e)
    if n % bn:
        return False
    itemsize = jnp.dtype(weight.dtype).itemsize
    if (_pick_bv(e, v, itemsize) is None
            or _pick_bv(e, v, itemsize, for_dw=True) is None):
        return False
    return (hidden.dtype in (jnp.float32, jnp.bfloat16)
            and weight.dtype == hidden.dtype
            and jnp.issubdtype(labels.dtype, jnp.integer))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, sel_ref, m_ref, l_ref,
                s_ref, *, nv, bv):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        s_ref[:] = jnp.zeros_like(s_ref)

    logits = jax.lax.dot(h_ref[...], w_ref[...],
                         preferred_element_type=jnp.float32)
    bn = logits.shape[0]
    col = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = col == lab_ref[:, :1]
    s_ref[:, :1] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1,
                            keepdims=True)
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    l_ref[:, :1] = (l_ref[:, :1] * jnp.exp(m_prev - m_new)
                    + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_ref[:, :1] = m_new

    @pl.when(iv == nv - 1)
    def _():
        lse = m_ref[:, :1] + jnp.log(l_ref[:, :1])
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        sel_ref[...] = jnp.broadcast_to(s_ref[:, :1], sel_ref.shape)


def _dh_kernel(h_ref, w_ref, lab_ref, lse_ref, g_ref, dh_ref, acc_ref,
               *, nv, bv):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w = w_ref[...]
    logits = jax.lax.dot(h_ref[...], w, preferred_element_type=jnp.float32)
    bn = logits.shape[0]
    p = jnp.exp(logits - lse_ref[:, :1])
    col = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    onehot = (col == lab_ref[:, :1]).astype(jnp.float32)
    dlog = ((p - onehot) * g_ref[:, :1]).astype(w.dtype)
    acc_ref[...] += jax.lax.dot_general(
        dlog, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(iv == nv - 1)
    def _():
        dh_ref[...] = acc_ref[...].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, lab_ref, lse_ref, g_ref, dw_ref, acc_ref,
               *, nb, bv):
    iv, ii = pl.program_id(0), pl.program_id(1)

    @pl.when(ii == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h = h_ref[...]
    logits = jax.lax.dot(h, w_ref[...], preferred_element_type=jnp.float32)
    bn = logits.shape[0]
    p = jnp.exp(logits - lse_ref[:, :1])
    col = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    onehot = (col == lab_ref[:, :1]).astype(jnp.float32)
    dlog = ((p - onehot) * g_ref[:, :1]).astype(h.dtype)
    acc_ref[...] += jax.lax.dot_general(
        h, dlog, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ii == nb - 1)
    def _():
        # f32 accumulation in scratch, emit in the weight dtype — the
        # [E, V] f32 intermediate (262 MB at bench shape) never exists
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


# ---------------------------------------------------------------------------
# raw calls (local shapes; also the per-shard lowering for _partition)
# ---------------------------------------------------------------------------

def _fwd_call(hidden, weight, lab_b):
    """(lse [n, 128], sel [n, 128]) — lane-replicated row stats."""
    n, e = hidden.shape
    v = weight.shape[1]
    bn = _pick_bn(n, e)
    bv = _pick_bv(e, v, jnp.dtype(weight.dtype).itemsize)
    nb, nv = n // bn, v // bv
    return pl.pallas_call(
        functools.partial(_fwd_kernel, nv=nv, bv=bv),
        grid=(nb, nv),
        in_specs=[
            pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
            pl.BlockSpec((e, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(hidden, weight, lab_b)


def _dh_call(hidden, weight, lab_b, lse_b, g_b):
    """dHidden [n, e] (hidden dtype)."""
    n, e = hidden.shape
    v = weight.shape[1]
    bn = _pick_bn(n, e)
    bv = _pick_bv(e, v, jnp.dtype(weight.dtype).itemsize)
    nb, nv = n // bn, v // bv
    return pl.pallas_call(
        functools.partial(_dh_kernel, nv=nv, bv=bv),
        grid=(nb, nv),
        in_specs=[
            pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
            pl.BlockSpec((e, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, e), hidden.dtype),
        scratch_shapes=[pltpu.VMEM((bn, e), jnp.float32)],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(hidden, weight, lab_b, lse_b, g_b)


def _dw_call(hidden, weight, lab_b, lse_b, g_b):
    """dW [e, v] in the weight dtype (f32-accumulated in VMEM)."""
    n, e = hidden.shape
    v = weight.shape[1]
    bn = _pick_bn(n, e)
    bv = _pick_bv(e, v, jnp.dtype(weight.dtype).itemsize, for_dw=True)
    nb, nv = n // bn, v // bv
    return pl.pallas_call(
        functools.partial(_dw_kernel, nb=nb, bv=bv),
        grid=(nv, nb),
        in_specs=[
            pl.BlockSpec((bn, e), lambda j, i: (i, 0)),
            pl.BlockSpec((e, bv), lambda j, i: (0, j)),
            pl.BlockSpec((bn, _LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, _LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, _LANES), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((e, bv), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((e, v), weight.dtype),
        scratch_shapes=[pltpu.VMEM((e, bv), jnp.float32)],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(hidden, weight, lab_b, lse_b, g_b)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------

def _lane(x, dtype=None):
    x = x if dtype is None else x.astype(dtype)
    return jnp.broadcast_to(x[:, None], (x.shape[0], _LANES))


def _fwd_dispatch(hidden, weight, lab_b, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.flce_fwd()(hidden, weight, lab_b)
    return _fwd_call(hidden, weight, lab_b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flce(part, hidden, weight, labels):
    lse, sel = _fwd_dispatch(hidden, weight, _lane(labels, jnp.int32), part)
    return lse[:, 0] - sel[:, 0]


def _flce_fwd(part, hidden, weight, labels):
    lab_b = _lane(labels, jnp.int32)
    lse, sel = _fwd_dispatch(hidden, weight, lab_b, part)
    return lse[:, 0] - sel[:, 0], (hidden, weight, lab_b, lse[:, 0])


def _flce_bwd(part, res, g):
    hidden, weight, lab_b, lse = res
    lse_b = _lane(lse)
    g_b = _lane(g.astype(jnp.float32))
    if part:
        from paddle_tpu.ops.pallas import _partition
        dh = _partition.flce_dh()(hidden, weight, lab_b, lse_b, g_b)
        dw = _partition.flce_dw()(hidden, weight, lab_b, lse_b, g_b)
    else:
        dh = _dh_call(hidden, weight, lab_b, lse_b, g_b)
        dw = _dw_call(hidden, weight, lab_b, lse_b, g_b)
    # astype is a no-op for the raw kernel (it emits weight dtype); it
    # covers partitioned fallbacks that produce f32
    return (dh, dw.astype(weight.dtype),
            jnp.zeros((hidden.shape[0],), dtype=jax.dtypes.float0))


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, *,
                               partitioned: bool = False):
    """Per-row loss ``lse(h_i·W) − (h_i·W)[labels[i]]`` for [N, E] hidden,
    [E, V] weight and int [N] labels — the [N, V] logits are never
    materialized. ``supported(hidden, weight, labels)`` must hold.
    Out-of-range labels (e.g. an ignore_index of −100) select nothing:
    their row loss is the bare lse (callers mask it) and contributes no
    onehot term to the gradients — combined with a zero cotangent from
    the caller's mask, ignored rows produce exactly zero grad.

    ``partitioned`` routes the three kernels through custom_partitioning
    (``_partition.flce_*``) so they run per shard on a multi-device mesh,
    including a Megatron vocab-sharded lm-head (local online lse + lse
    merge over the vocab axes, dW sharded over vocab, dH psum-reduced).
    """
    return _flce(bool(partitioned), hidden, weight, labels)


# ---------------------------------------------------------------------------
# chunked XLA reference (fallback + the honest competitor to microbench)
# ---------------------------------------------------------------------------

def chunked_linear_cross_entropy(hidden, weight, labels,
                                 block_v: int = 4096):
    """Pure-XLA vocab-chunked variant: lax.scan over V tiles with an
    online logsumexp carry, ``jax.checkpoint`` on the body so backward
    recomputes each tile instead of saving it. Same O(N) loss-path
    memory as the Pallas kernel; used as the dispatch fallback for
    unsupported shapes and as the microbench competitor that keeps the
    kernel honest (BASELINE.md's DISPATCH_MAX_V methodology)."""
    n, e = hidden.shape
    v = weight.shape[1]
    block_v = min(block_v, v)
    nv, rem = divmod(v, block_v)
    lab = labels.astype(jnp.int32)

    @jax.checkpoint
    def merge(carry, w_c, off):
        m, l, s = carry
        logits = jnp.dot(hidden, w_c,
                         preferred_element_type=jnp.float32)  # [n, bv]
        col = off + jnp.arange(w_c.shape[1], dtype=jnp.int32)[None, :]
        s = s + jnp.sum(jnp.where(col == lab[:, None], logits, 0.0), axis=1)
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        return m_new, l, s

    carry = (jnp.full((n,), _NEG_INF, jnp.float32),
             jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    if nv:
        w_t = (weight[:, :nv * block_v]
               .reshape(e, nv, block_v).transpose(1, 0, 2))  # [nv, e, bv]
        offs = jnp.arange(nv, dtype=jnp.int32) * block_v
        carry, _ = jax.lax.scan(
            lambda c, xs: (merge(c, *xs), None), carry, (w_t, offs))
    if rem:
        # ragged tail chunk handled out-of-scan with the same online
        # merge — any V works without padding (a zero-pad would corrupt
        # the lse) or degrading to full-vocab tiles
        carry = merge(carry, weight[:, nv * block_v:],
                      jnp.int32(nv * block_v))
    m, l, s = carry
    return m + jnp.log(l) - s
