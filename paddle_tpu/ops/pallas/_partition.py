"""Multi-chip dispatch for the Pallas kernel set.

The reference runs its fused CUDA kernels under the multi-device executor
(``paddle/fluid/operators/fused/multihead_matmul_op.cu`` launched per
device by ``framework/parallel_executor.cc:504``). The TPU-native
equivalent: each Pallas call-unit is wrapped in
``jax.experimental.custom_partitioning`` so the SPMD partitioner (Shardy
or GSPMD) runs the kernel *per shard* inside jit over a multi-device mesh
instead of falling back to the dense jnp path.

Design per unit:

- a **sharding rule** (einsum-like string) tells Shardy how shardings
  propagate through the op — batch-like factors pass through, row-stat
  lane factors and normalized/contracted dims need replication;
- a **sanitizing partition()** is the enforcement layer: whatever the
  partitioner suggests, it returns arg/result shardings the kernel can
  actually run on (dims the kernel reduces over are forced replicated,
  GQA head shardings must divide the kv heads, batch shardings must
  divide the batch). The partitioner inserts the reshards/collectives to
  match — this is load-bearing because explicitly committed input
  shardings are *not* auto-gathered to satisfy ``need_replication``
  factors;
- the **per-shard lowering** calls the raw kernel on local shapes, with
  a jnp fallback when a shard's row count breaks the kernel's block
  alignment, and emits the cross-shard collectives (psum of dw/db,
  log-sum-exp combine over a sharded vocab) itself.

Factories are keyed on the static config (lru_cache) so one
custom_partitioning object is reused per (causal, scale, blocks, ...)
combination and jit caches stay warm.
"""

from __future__ import annotations

import collections
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

LANES = 128

# Lowering decisions, keyed "<unit>:<kernel|fallback>". Recorded into the
# multichip driver artifact so "the Pallas path executed under sharding"
# is a checkable claim, not an assumption.
stats: collections.Counter = collections.Counter()


def reset_stats() -> None:
    stats.clear()


def _mod(name: str):
    """Submodule import immune to the package __init__ re-exporting a
    function under the same name (``pallas.flash_attention`` is the
    function once the package is initialized)."""
    import importlib
    return importlib.import_module(f"paddle_tpu.ops.pallas.{name}")


# ---------------------------------------------------------------------------
# small spec helpers
# ---------------------------------------------------------------------------

def _axes(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def _size(mesh, entry) -> int:
    s = 1
    for a in _axes(entry):
        s *= mesh.shape[a]
    return s


def _spec_entries(sharding, ndim) -> list:
    spec = tuple(getattr(sharding, "spec", ()) or ())
    out = list(spec[:ndim])
    return out + [None] * (ndim - len(out))


def _sharding_of(arg):
    sh = getattr(arg, "sharding", None)
    return sh if isinstance(sh, NamedSharding) else None


def _mesh_from(arg_shapes, fallback_mesh):
    for a in arg_shapes:
        sh = _sharding_of(a)
        if sh is not None:
            return sh.mesh
    return fallback_mesh


def _rows_aligned(n_local: int, block: int) -> bool:
    """Kernel row blocks are min(block, n) — a shard is runnable when its
    row count still tiles (and stays sublane-aligned)."""
    if n_local <= 0 or n_local % 8:
        return False
    return n_local <= block or n_local % block == 0


def _valid_dim(mesh, entry, dim_size: int, used: set) -> object:
    """Keep a suggested dim sharding only if it divides the dim and does
    not reuse an axis already consumed by another dim of the same spec."""
    ax = _axes(entry)
    if not ax or set(ax) & used:
        return None
    s = _size(mesh, entry)
    if s <= 1 or dim_size % s:
        return None
    used.update(ax)
    return entry


def _build(global_fn, plan, rule, *, need_replication=(), reduction=(),
           factor_sizes=None):
    """Wire a pallas call-unit into custom_partitioning.

    ``plan(mesh, arg_shapes) -> (arg_specs, out_specs, ctx)`` makes the
    sharding decision; ``global_fn(ctx, *args)`` is also the per-shard
    lowering (ctx carries the axes it must psum over / whether to take
    the jnp fallback).
    """
    cp = custom_partitioning(lambda *args: global_fn(None, *args))

    def partition(mesh, arg_shapes, result_shape):
        nmesh = _mesh_from(arg_shapes, mesh)
        arg_specs, out_specs, ctx = plan(nmesh, arg_shapes)
        out_sh = tuple(NamedSharding(nmesh, s) for s in out_specs)
        if not isinstance(result_shape, (tuple, list)):
            out_sh = out_sh[0]
        arg_sh = tuple(NamedSharding(nmesh, s) for s in arg_specs)
        return nmesh, functools.partial(global_fn, ctx), out_sh, arg_sh

    def infer(mesh, arg_shapes, result_shape):
        nmesh = _mesh_from(arg_shapes, mesh)
        _, out_specs, _ = plan(nmesh, arg_shapes)
        out_sh = tuple(NamedSharding(nmesh, s) for s in out_specs)
        if not isinstance(result_shape, (tuple, list)):
            return out_sh[0]
        return out_sh

    cp.def_partition(partition=partition,
                     infer_sharding_from_operands=infer,
                     sharding_rule=rule,
                     need_replication_factors=tuple(need_replication),
                     reduction_factors=tuple(reduction),
                     **(factor_sizes or {}))
    return cp


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _batch_head_plan(mesh, B, Hq, Hkv, b_entry, h_entry):
    """Shared batch/head sharding selection for the attention units:
    shard batch and heads, everything else replicated. The head
    sharding must divide BOTH head counts so each shard keeps whole GQA
    groups (contiguous blocks: q heads [i·Hq/s, …) ↔ kv heads
    [i·Hkv/s, …))."""
    used: set = set()
    b = _valid_dim(mesh, b_entry, B, used)
    h = h_entry
    if _size(mesh, h) > 1 and (Hkv % _size(mesh, h) or Hq % _size(mesh, h)):
        h = None
    h = _valid_dim(mesh, h, math.gcd(Hq, Hkv), used)
    return b, h


def _flash_plan(mesh, arg_shapes):
    B, Hq = arg_shapes[0].shape[0], arg_shapes[0].shape[1]
    Hkv = arg_shapes[1].shape[1]
    qspec = _spec_entries(_sharding_of(arg_shapes[0]), 4)
    kspec = _spec_entries(_sharding_of(arg_shapes[1]), 4)
    return _batch_head_plan(mesh, B, Hq, Hkv, qspec[0] or kspec[0],
                            qspec[1] or kspec[1])


@functools.lru_cache(maxsize=None)
def flash_fwd(causal: bool, scale: float, block_q, block_k, group: int):
    FA = _mod("flash_attention")

    def fn(ctx, qt, kt, vt):
        stats["flash_fwd:kernel"] += 1
        return FA._fwd(qt, kt, vt, causal, scale, block_q, block_k)

    def plan(mesh, arg_shapes):
        b, h = _flash_plan(mesh, arg_shapes)
        io = P(b, h, None, None)
        return (io, io, io), (io, io), None

    if group > 1:
        rule = ("b (h g) t d, b h s e, b h s e "
                "-> b (h g) t d, b (h g) t l")
        sizes = {"g": group}
    else:
        rule = "b h t d, b h s e, b h s e -> b h t d, b h t l"
        sizes = None
    return _build(fn, plan, rule,
                  # sorted by factor first-appearance (Shardy requirement)
                  need_replication=("t", "d", "s", "e", "l"),
                  factor_sizes=sizes)


@functools.lru_cache(maxsize=None)
def flash_bwd(causal: bool, scale: float, block_q, block_k, group: int):
    FA = _mod("flash_attention")

    def fn(ctx, qt, kt, vt, ot, lse, do_t):
        stats["flash_bwd:kernel"] += 1
        return FA._bwd_impl(qt, kt, vt, ot, lse, do_t, causal, scale,
                            block_q, block_k)

    def plan(mesh, arg_shapes):
        b, h = _flash_plan(mesh, arg_shapes)
        q_like = P(b, h, None, None)
        kv_like = P(b, h, None, None)
        args = (q_like, kv_like, kv_like, q_like, q_like, q_like)
        outs = (q_like, kv_like, kv_like)
        return args, outs, None

    if group > 1:
        rule = ("b (h g) t d, b h s e, b h s e, b (h g) t d, b (h g) t l, "
                "b (h g) t d -> b (h g) t d, b h s e, b h s e")
        sizes = {"g": group}
    else:
        rule = ("b h t d, b h s e, b h s e, b h t d, b h t l, b h t d "
                "-> b h t d, b h s e, b h s e")
        sizes = None
    return _build(fn, plan, rule,
                  # sorted by factor first-appearance (Shardy requirement)
                  need_replication=("t", "d", "s", "e", "l"),
                  factor_sizes=sizes)


# ---------------------------------------------------------------------------
# row norms (rms / layer norm) — 2D [n, h] units
# ---------------------------------------------------------------------------

def _rows_plan(mesh, x_arg, block_rows):
    """Row sharding passes through; feature dim replicated. ctx = (row
    axes for psum, use_kernel)."""
    n = x_arg.shape[0]
    spec = _spec_entries(_sharding_of(x_arg), 2)
    used: set = set()
    r = _valid_dim(mesh, spec[0], n, used)
    n_local = n // _size(mesh, r) if r is not None else n
    return r, _axes(r), _rows_aligned(n_local, block_rows)


@functools.lru_cache(maxsize=None)
def rms_fwd(eps: float):
    N = _mod("norm")

    def fn(ctx, x2d, w):
        use_kernel = ctx is None or ctx[1]
        if use_kernel:
            stats["rms_fwd:kernel"] += 1
            return N._rms_fwd(x2d, w, eps)
        stats["rms_fwd:fallback"] += 1
        xf = x2d.astype(jnp.float32)
        rstd = jax.lax.rsqrt(jnp.mean(xf * xf, axis=1, keepdims=True) + eps)
        y = (xf * rstd * w.astype(jnp.float32)).astype(x2d.dtype)
        return y, jnp.broadcast_to(rstd, (x2d.shape[0], LANES))

    def plan(mesh, arg_shapes):
        r, raxes, ok = _rows_plan(mesh, arg_shapes[0], N._BLOCK_ROWS)
        return ((P(r, None), P(None)),
                (P(r, None), P(r, None)),
                (raxes, ok))

    return _build(fn, plan, "n h, h -> n h, n l",
                  need_replication=("h", "l"))


@functools.lru_cache(maxsize=None)
def rms_bwd(eps: float):
    N = _mod("norm")

    def fn(ctx, x2d, w, rstd, g):
        raxes, use_kernel = ctx if ctx is not None else ((), True)
        if use_kernel:
            stats["rms_bwd:kernel"] += 1
            dx, dw = N._rms_bwd_call(x2d, w, rstd, g)
        else:
            stats["rms_bwd:fallback"] += 1
            xf = x2d.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            wf = w.astype(jnp.float32)
            rs = rstd[:, :1]
            xhat = xf * rs
            wg = gf * wf
            c = jnp.mean(wg * xhat, axis=1, keepdims=True)
            dx = (rs * (wg - xhat * c)).astype(x2d.dtype)
            dw = jnp.sum(gf * xhat, axis=0)
        if raxes:
            dw = jax.lax.psum(dw, raxes)
        return dx, dw

    def plan(mesh, arg_shapes):
        r, raxes, ok = _rows_plan(mesh, arg_shapes[0], N._BLOCK_ROWS)
        return ((P(r, None), P(None), P(r, None), P(r, None)),
                (P(r, None), P(None)),
                (raxes, ok))

    return _build(fn, plan, "n h, h, n l, n h -> n h, h",
                  need_replication=("h", "l"))


@functools.lru_cache(maxsize=None)
def ln_fwd(eps: float):
    N = _mod("norm")

    def fn(ctx, x2d, w, b):
        use_kernel = ctx is None or ctx[1]
        if use_kernel:
            stats["ln_fwd:kernel"] += 1
            return N._ln_fwd(x2d, w, b, eps)
        stats["ln_fwd:fallback"] += 1
        xf = x2d.astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = (xf - mean) * rstd
        y = (xhat * w.astype(jnp.float32)
             + b.astype(jnp.float32)).astype(x2d.dtype)
        n = x2d.shape[0]
        return (y, jnp.broadcast_to(mean, (n, LANES)),
                jnp.broadcast_to(rstd, (n, LANES)))

    def plan(mesh, arg_shapes):
        r, raxes, ok = _rows_plan(mesh, arg_shapes[0], N._BLOCK_ROWS)
        return ((P(r, None), P(None), P(None)),
                (P(r, None), P(r, None), P(r, None)),
                (raxes, ok))

    return _build(fn, plan, "n h, h, h -> n h, n l, n l",
                  need_replication=("h", "l"))


@functools.lru_cache(maxsize=None)
def ln_bwd(eps: float):
    N = _mod("norm")

    def fn(ctx, x2d, w, mean, rstd, g):
        raxes, use_kernel = ctx if ctx is not None else ((), True)
        if use_kernel:
            stats["ln_bwd:kernel"] += 1
            dx, dw, db = N._ln_bwd_call(x2d, w, mean, rstd, g)
        else:
            stats["ln_bwd:fallback"] += 1
            xf = x2d.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            wf = w.astype(jnp.float32)
            mu, rs = mean[:, :1], rstd[:, :1]
            xhat = (xf - mu) * rs
            wg = gf * wf
            c1 = jnp.mean(wg, axis=1, keepdims=True)
            c2 = jnp.mean(wg * xhat, axis=1, keepdims=True)
            dx = (rs * (wg - c1 - xhat * c2)).astype(x2d.dtype)
            dw = jnp.sum(gf * xhat, axis=0)
            db = jnp.sum(gf, axis=0)
        if raxes:
            dw = jax.lax.psum(dw, raxes)
            db = jax.lax.psum(db, raxes)
        return dx, dw, db

    def plan(mesh, arg_shapes):
        r, raxes, ok = _rows_plan(mesh, arg_shapes[0], N._BLOCK_ROWS)
        return ((P(r, None), P(None), P(r, None), P(r, None), P(r, None)),
                (P(r, None), P(None), P(None)),
                (raxes, ok))

    return _build(fn, plan, "n h, h, n l, n l, n h -> n h, h, h",
                  need_replication=("h", "l"))


# ---------------------------------------------------------------------------
# softmax cross-entropy — [n, v] units
# ---------------------------------------------------------------------------

def _xent_plan(mesh, x_arg, *, shard_v: bool):
    X = _mod("softmax_xent")

    n, v = x_arg.shape
    spec = _spec_entries(_sharding_of(x_arg), 2)
    used: set = set()
    r = _valid_dim(mesh, spec[0], n, used)
    vv = _valid_dim(mesh, spec[1], v, used) if shard_v else None
    if vv is not None and (v // _size(mesh, vv)) % X._BLOCK_V:
        used.difference_update(_axes(vv))
        vv = None
    n_local = n // _size(mesh, r) if r is not None else n
    ok = _rows_aligned(n_local, X._BLOCK_N)
    return r, vv, _axes(vv), ok


@functools.lru_cache(maxsize=None)
def xent_lse():
    """Row log-sum-exp over [n, v] (lane-replicated [n, 128] out). The
    vocab dim may be sharded (Megatron-style tp lm-head): each shard
    computes its local lse and the shards combine with the standard
    max/psum log-sum-exp merge over the vocab axes."""
    X = _mod("softmax_xent")

    def fn(ctx, logits):
        vaxes, use_kernel = ctx if ctx is not None else ((), True)
        if use_kernel and logits.shape[1] % X._BLOCK_V == 0:
            stats["xent_lse:kernel"] += 1
            lse = X._lse_call(logits)
        else:
            stats["xent_lse:fallback"] += 1
            red = jax.nn.logsumexp(logits.astype(jnp.float32), axis=1,
                                   keepdims=True)
            lse = jnp.broadcast_to(red, (logits.shape[0], LANES))
        if vaxes:
            m = jax.lax.pmax(lse, vaxes)
            lse = m + jnp.log(jax.lax.psum(jnp.exp(lse - m), vaxes))
        return lse

    def plan(mesh, arg_shapes):
        r, vv, vaxes, ok = _xent_plan(mesh, arg_shapes[0], shard_v=True)
        return ((P(r, vv),), (P(r, None),), (vaxes, ok))

    return _build(fn, plan, "n v -> n l",
                  need_replication=("l",), reduction=("v",))


@functools.lru_cache(maxsize=None)
def xent_dx():
    """softmax·g over [n, v] given lane-replicated lse/g — elementwise in
    v, so both n and v shard cleanly."""
    X = _mod("softmax_xent")

    def fn(ctx, logits, lse_b, g_b):
        use_kernel = ctx is None or ctx[1]
        if use_kernel and logits.shape[1] % X._BLOCK_V == 0:
            stats["xent_dx:kernel"] += 1
            return X._dx_call(logits, lse_b, g_b)
        stats["xent_dx:fallback"] += 1
        return (jnp.exp(logits.astype(jnp.float32) - lse_b[:, :1])
                * g_b[:, :1]).astype(logits.dtype)

    def plan(mesh, arg_shapes):
        r, vv, _, ok = _xent_plan(mesh, arg_shapes[0], shard_v=True)
        return ((P(r, vv), P(r, None), P(r, None)), (P(r, vv),), ((), ok))

    return _build(fn, plan, "n v, n l, n l -> n v",
                  need_replication=("l",))


# ---------------------------------------------------------------------------
# fused linear ⊗ cross-entropy — (h [n, e], w [e, v]) units
# ---------------------------------------------------------------------------

def _flce_plan(mesh, h_arg, w_arg):
    """Rows shard from h dim0; vocab shards from w dim1 (Megatron tp
    lm-head); the contracted e dim is forced replicated (the partitioner
    all-gathers a ZeRO-sharded weight, exactly as the dense matmul path
    would). ctx = (vaxes, vsizes, raxes, use_kernel)."""
    X = _mod("linear_xent")
    n, e = h_arg.shape
    v = w_arg.shape[1]
    hspec = _spec_entries(_sharding_of(h_arg), 2)
    wspec = _spec_entries(_sharding_of(w_arg), 2)
    used: set = set()
    r = _valid_dim(mesh, hspec[0], n, used)
    vv = _valid_dim(mesh, wspec[1], v, used)
    n_local = n // _size(mesh, r) if r is not None else n
    v_local = v // _size(mesh, vv) if vv is not None else v
    itemsize = jnp.dtype(w_arg.dtype).itemsize
    ok = (e % LANES == 0 and n_local % 8 == 0
          and n_local % X._pick_bn(n_local, e) == 0
          and X._pick_bv(e, v_local, itemsize) is not None
          and X._pick_bv(e, v_local, itemsize, for_dw=True) is not None)
    vsizes = tuple(mesh.shape[a] for a in _axes(vv))
    return r, vv, (_axes(vv), vsizes, _axes(r), ok)


def _flce_shift(lab_b, vaxes, vsizes, v_local):
    """Global→local label shift for a vocab-sharded weight: subtract this
    shard's column offset (row-major over the vocab axes). Out-of-range
    rows (another shard's labels, or an ignore_index) select nothing."""
    if not vaxes:
        return lab_b
    idx = jnp.int32(0)
    for a, s in zip(vaxes, vsizes):
        idx = idx * s + jax.lax.axis_index(a)
    return lab_b - idx * v_local


def _flce_fallback_fwd(h, w, lab_local):
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32)
    n, v_local = logits.shape
    lse = jax.nn.logsumexp(logits, axis=1, keepdims=True)
    lab = lab_local[:, :1]
    in_range = (lab >= 0) & (lab < v_local)
    safe = jnp.clip(lab, 0, v_local - 1)
    sel = jnp.take_along_axis(logits, safe, axis=1)
    sel = jnp.where(in_range, sel, 0.0)
    return (jnp.broadcast_to(lse, (n, LANES)),
            jnp.broadcast_to(sel, (n, LANES)))


def _flce_fallback_dlog(h, w, lab_local, lse_b, g_b):
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_b[:, :1])
    v_local = logits.shape[1]
    col = jnp.arange(v_local, dtype=jnp.int32)[None, :]
    onehot = (col == lab_local[:, :1]).astype(jnp.float32)
    return (p - onehot) * g_b[:, :1]


@functools.lru_cache(maxsize=None)
def flce_fwd():
    """(lse [n, 128], sel [n, 128]) from (h, w, lab). A sharded vocab
    combines with the standard max/psum log-sum-exp merge; sel is a psum
    (exactly one shard holds each in-range label)."""
    X = _mod("linear_xent")

    def fn(ctx, h, w, lab_b):
        vaxes, vsizes, _, use_kernel = ctx if ctx is not None \
            else ((), (), (), True)
        lab_local = _flce_shift(lab_b, vaxes, vsizes, w.shape[1])
        if use_kernel:
            stats["flce_fwd:kernel"] += 1
            lse, sel = X._fwd_call(h, w, lab_local)
        else:
            stats["flce_fwd:fallback"] += 1
            lse, sel = _flce_fallback_fwd(h, w, lab_local)
        if vaxes:
            m = jax.lax.pmax(lse, vaxes)
            lse = m + jnp.log(jax.lax.psum(jnp.exp(lse - m), vaxes))
            sel = jax.lax.psum(sel, vaxes)
        return lse, sel

    def plan(mesh, arg_shapes):
        r, vv, ctx = _flce_plan(mesh, arg_shapes[0], arg_shapes[1])
        return ((P(r, None), P(None, vv), P(r, None)),
                (P(r, None), P(r, None)), ctx)

    return _build(fn, plan, "n e, e v, n l -> n l, n l",
                  need_replication=("e", "l"), reduction=("v",))


@functools.lru_cache(maxsize=None)
def flce_dh():
    """dHidden [n, e]: each vocab shard contributes its tile-recomputed
    ``dlogits @ Wᵀ`` partial; psum over the vocab axes."""
    X = _mod("linear_xent")

    def fn(ctx, h, w, lab_b, lse_b, g_b):
        vaxes, vsizes, _, use_kernel = ctx if ctx is not None \
            else ((), (), (), True)
        lab_local = _flce_shift(lab_b, vaxes, vsizes, w.shape[1])
        if use_kernel:
            stats["flce_dh:kernel"] += 1
            dh = X._dh_call(h, w, lab_local, lse_b, g_b)
        else:
            stats["flce_dh:fallback"] += 1
            dlog = _flce_fallback_dlog(h, w, lab_local, lse_b, g_b)
            dh = jax.lax.dot_general(
                dlog.astype(w.dtype), w, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32).astype(h.dtype)
        if vaxes:
            dh = jax.lax.psum(dh, vaxes)
        return dh

    def plan(mesh, arg_shapes):
        r, vv, ctx = _flce_plan(mesh, arg_shapes[0], arg_shapes[1])
        io = (P(r, None), P(None, vv), P(r, None), P(r, None), P(r, None))
        return io, (P(r, None),), ctx

    return _build(fn, plan, "n e, e v, n l, n l, n l -> n e",
                  need_replication=("e", "l"), reduction=("v",))


@functools.lru_cache(maxsize=None)
def flce_dw():
    """dW [e, v] (weight dtype): vocab-sharded output; row-sharded
    inputs psum their partials over the row axes (f32 for the combine)."""
    X = _mod("linear_xent")

    def fn(ctx, h, w, lab_b, lse_b, g_b):
        vaxes, vsizes, raxes, use_kernel = ctx if ctx is not None \
            else ((), (), (), True)
        lab_local = _flce_shift(lab_b, vaxes, vsizes, w.shape[1])
        if use_kernel:
            stats["flce_dw:kernel"] += 1
            dw = X._dw_call(h, w, lab_local, lse_b, g_b)
        else:
            stats["flce_dw:fallback"] += 1
            dlog = _flce_fallback_dlog(h, w, lab_local, lse_b, g_b)
            dw = jax.lax.dot_general(
                h, dlog.astype(h.dtype), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(w.dtype)
        if raxes:
            dw = jax.lax.psum(dw.astype(jnp.float32),
                              raxes).astype(w.dtype)
        return dw

    def plan(mesh, arg_shapes):
        r, vv, ctx = _flce_plan(mesh, arg_shapes[0], arg_shapes[1])
        io = (P(r, None), P(None, vv), P(r, None), P(r, None), P(r, None))
        return io, (P(None, vv),), ctx

    return _build(fn, plan, "n e, e v, n l, n l, n l -> e v",
                  need_replication=("e", "l"), reduction=("n",))


# ---------------------------------------------------------------------------
# rotary embedding — [B, T, H, D] with [T, D/2] tables
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def rope(sign: float):
    R = _mod("rope")

    def fn(ctx, x, cos, sin):
        use_kernel = ctx is None or ctx[1]
        if use_kernel:
            stats["rope:kernel"] += 1
            return R._rope_call(x, cos, sin, sign)
        stats["rope:fallback"] += 1
        d2 = x.shape[-1] // 2
        x1, x2 = x[..., :d2], x[..., d2:]
        c = cos[None, :, None, :]
        s = sin[None, :, None, :] * sign
        x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
        return jnp.concatenate(
            [x1f * c - x2f * s, x2f * c + x1f * s], axis=-1).astype(x.dtype)

    def plan(mesh, arg_shapes):
        B, T, H, D = arg_shapes[0].shape
        spec = _spec_entries(_sharding_of(arg_shapes[0]), 4)
        used: set = set()
        b = _valid_dim(mesh, spec[0], B, used)
        t = _valid_dim(mesh, spec[1], T, used)
        h = _valid_dim(mesh, spec[2], H, used)
        t_local = T // _size(mesh, t) if t is not None else T
        ok = _rows_aligned(t_local, R._BLOCK_T)
        # the tables shard with the sequence so each shard rotates by its
        # own absolute positions
        return ((P(b, t, h, None), P(t, None), P(t, None)),
                (P(b, t, h, None),), ((), ok))

    return _build(fn, plan, "b t h d, t e, t e -> b t h d",
                  need_replication=("d", "e"))


# ---------------------------------------------------------------------------
# selective scan (Mamba) — [B, T, Ei] with [N, Ei] state matrix
# ---------------------------------------------------------------------------

def _ss_plan(mesh, arg_shapes):
    """Batch and channel (lane) dims shard; time is sequential and the
    state dim lives on sublanes — both replicated. Channel shardings must
    keep each shard lane-tiled (Ei_local % 128), else they are dropped
    (the kernel then runs on the full channel width per batch shard)."""
    Bsz, T, Ei = arg_shapes[0].shape
    spec = _spec_entries(_sharding_of(arg_shapes[0]), 3)
    used: set = set()
    b = _valid_dim(mesh, spec[0], Bsz, used)
    e = spec[2]
    if _size(mesh, e) > 1 and (Ei // _size(mesh, e)) % LANES:
        e = None
    e = _valid_dim(mesh, e, Ei, used)
    return b, e


@functools.lru_cache(maxsize=None)
def selective_scan_fwd(k: int):
    SS = _mod("selective_scan")

    def fn(ctx, u, delta, At, B, C, D2):
        stats["selective_scan_fwd:kernel"] += 1
        return SS._fwd_call(u, delta, At, B, C, D2, k)

    def plan(mesh, arg_shapes):
        b, e = _ss_plan(mesh, arg_shapes)
        te = P(b, None, e)
        tn = P(b, None, None)
        args = (te, te, P(None, e), tn, tn, P(None, e))
        outs = (te, P(b, None, None, e))
        return args, outs, None

    # factors: b t e (u) | n (A.T) | o (the D row dim) | c (chunk count,
    # result-only); t/n sequential/sublane -> replicated
    return _build(fn, plan,
                  "b t e, b t e, n e, b t n, b t n, o e "
                  "-> b t e, b c n e",
                  need_replication=("t", "n", "o", "c"))


@functools.lru_cache(maxsize=None)
def selective_scan_bwd(k: int):
    SS = _mod("selective_scan")

    def fn(ctx, u, delta, At, B, C, h0, dy):
        stats["selective_scan_bwd:kernel"] += 1
        du, ddt, dB, dC, dA_part = SS._bwd_call(u, delta, At, B, C, h0,
                                                dy, k)
        caxes = ctx if ctx is not None else ()
        if caxes:
            # dB/dC reduce over channels; with channels sharded each
            # shard holds a partial sum
            dB = jax.lax.psum(dB, caxes)
            dC = jax.lax.psum(dC, caxes)
        return du, ddt, dB, dC, dA_part

    def plan(mesh, arg_shapes):
        b, e = _ss_plan(mesh, arg_shapes)
        te = P(b, None, e)
        tn = P(b, None, None)
        args = (te, te, P(None, e), tn, tn, P(b, None, None, e), te)
        outs = (te, te, tn, tn, P(b, None, e))
        return args, outs, _axes(e)

    return _build(fn, plan,
                  "b t e, b t e, n e, b t n, b t n, b c n e, b t e "
                  "-> b t e, b t e, b t n, b t n, b n e",
                  need_replication=("t", "n", "c"))


# ---------------------------------------------------------------------------
# decode attention (serving): shard over batch + kv heads
# ---------------------------------------------------------------------------

def _decode_plan(mesh, arg_shapes):
    """args: (sp [2], q2 [B,Hq,D], kn2 [B,Hkv,D], vn2, kc [L,B,Hkv,S,D],
    vc, [ks [L,B,Hkv,S], vs]). Shard batch + heads (whole GQA groups);
    layer/seq/head_dim and the scalar-prefetch vector replicated."""
    B, Hq = arg_shapes[1].shape[0], arg_shapes[1].shape[1]
    Hkv = arg_shapes[2].shape[1]
    qspec = _spec_entries(_sharding_of(arg_shapes[1]), 3)
    cspec = _spec_entries(_sharding_of(arg_shapes[4]), 5)
    return _batch_head_plan(mesh, B, Hq, Hkv, qspec[0] or cspec[1],
                            qspec[1] or cspec[2])


@functools.lru_cache(maxsize=None)
def decode_attn(scale: float, group: int, quantized: bool):
    DA = _mod("decode_attention")

    def fn(ctx, sp, q2, kn2, vn2, *cache):
        stats["decode_attn:kernel"] += 1
        return DA.raw_call(sp, q2, kn2, vn2, *cache, scale=scale)

    def plan(mesh, arg_shapes):
        b, h = _decode_plan(mesh, arg_shapes)
        q_like = P(b, h, None)
        kv_like = P(b, h, None)
        c_like = P(None, b, h, None, None)
        args = [P(None), q_like, kv_like, kv_like, c_like, c_like]
        if quantized:
            args += [P(None, b, h, None), P(None, b, h, None)]
        return tuple(args), (q_like,), None

    hq = "(h g)" if group > 1 else "h"
    if quantized:
        rule = (f"z, b {hq} d, b h d, b h d, l b h s d, l b h s d, "
                f"l b h s, l b h s -> b {hq} d")
    else:
        rule = (f"z, b {hq} d, b h d, b h d, l b h s d, l b h s d "
                f"-> b {hq} d")
    return _build(fn, plan, rule,
                  need_replication=("z", "d", "l", "s"),
                  factor_sizes=({"g": group} if group > 1 else None))
