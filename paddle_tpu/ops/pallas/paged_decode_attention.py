"""Page-table-aware single-token decode attention over the paged pool.

The paged serving engine's decode step today materializes a contiguous
per-slot cache view with ``models.generation.paged_gather`` — a full
copy of every live page, every layer, every step — and only then runs
attention over the copy. This kernel deletes the copy the same way
``decode_attention`` deleted the per-layer ``lax.scan`` slice: the page
indirection moves INTO the pallas index maps. The scalar-prefetch row
carries ``[layer, index, table...]``, and the page-block index map

    page id = sp_ref[b, 2 + min(max(j - 1, 0), last_live_page)]

reads the slot's device-resident page table directly — grid step ``j``
DMAs physical page ``table[j - 1]`` of the pool, so the persistent HBM
(the pool) is the only cache the kernel ever touches. Blocks past the
filled prefix repeat the last live page id and Mosaic elides the
repeated DMA, exactly the stacked-layer clamp trick.

Everything else is the ``decode_attention`` recipe on a page-shaped
block: the fresh token's raw k/v joins the streaming softmax as grid
step 0; pages stream as steps 1..M with positions ``>= index`` masked
(position ``p`` lives in page ``p // P`` at offset ``p % P``, matching
``paged_gather``'s view); one block-diagonal all-heads dot per page;
int8 pool scales fold into the logit/prob planes so HBM traffic stays
the int8 bytes.

Pool layout contract matches ``models.generation.init_paged_cache``:
k/v leaves ``[num_pages + 1, L, Hkv, P, D]`` (page id 0 = the reserved
null page), int8 layout adds f32 scale leaves
``[num_pages + 1, L, Hkv, P]``. ``table`` is one slot's int32 page-id
row — the same row the ``FLAGS_gen_device_pt`` engine keeps device-
resident, which is what makes "index maps read the page table" a
zero-upload statement end to end.

Status: interpreter-mode tests (``tests/test_paged_decode_attention.py``)
pin the kernel bit-exact to ``paged_gather`` + masked attention per
slot, under ``jax.vmap``, and for the int8 4-leaf layout — the
hardware-independent result. Wiring it under the engine's compiled
step (replacing the gather inside ``forward_with_cache``) and the TPU
timing run are the honest remaining caveat; off-TPU callers take the
``paged_reference`` einsum fallback under the same ``supported()`` gate
as the stacked kernel. Multi-device meshes fall back too (no
custom_partitioning wrapper yet — the pool's KV-head shard would need a
per-shard grid).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

LANES = 128
NEG_INF = -1e30


def supported(q, pool, table) -> bool:
    """Kernel gate; callers fall back to :func:`paged_reference` when
    False. ``q`` [B, 1, Hq, D] (decode chunks only); ``pool`` the paged
    leaves ([N, L, Hkv, P, D], int8 adds [N, L, Hkv, P] scales);
    ``table`` [B, M] int32 page rows. Raw dispatch only — a
    multi-device mesh has no partitioned wrapper for the paged layout
    yet, so it stays on the gather+einsum path."""
    if _support.dispatch_mode() != "raw":
        return False
    if q.ndim != 4 or q.shape[1] != 1:
        return False
    B, T, Hq, D = q.shape
    k = pool[0]
    if k.ndim != 5:
        return False
    _, _, Hkv, P, Dk = k.shape
    if Dk != D or D not in (64, 128, 256) or Hq % Hkv:
        return False
    if P % 8 or table.ndim != 2 or table.shape[0] != B:
        return False
    if _support.on_tpu() and not _support.interpret() and (Hkv * P) % LANES:
        return False                  # lane-aligned page blocks only
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    quantized = len(pool) == 4
    if quantized and k.dtype != jnp.int8:
        return False
    if not quantized and k.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    return True


def _kernel(sp_ref, q_ref, kn_ref, vn_ref, kp_ref, vp_ref, *rest,
            scale, P, M, G, Hkv, quantized, out_dtype):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    idx = sp_ref[b, 1]

    @pl.when(j == 0)
    def _fresh():
        # the step's own token: p = exp(s - m) = 1, l = 1, acc = v_new
        q = q_ref[0].astype(jnp.float32)            # [Hq, D]
        kn = kn_ref[0].astype(jnp.float32)          # [Hkv, D]
        vn = vn_ref[0].astype(jnp.float32)
        for h in range(Hkv):
            rows = slice(h * G, (h + 1) * G)
            s_h = jnp.sum(q[rows] * kn[h:h + 1], axis=1,
                          keepdims=True) * scale    # [G, 1]
            m_ref[rows, :] = jnp.broadcast_to(s_h, (G, LANES))
            acc_ref[rows, :] = jnp.broadcast_to(vn[h:h + 1],
                                                (G, vn.shape[1]))
        l_ref[:, :] = jnp.ones_like(l_ref)

    last_page = jnp.maximum(idx - 1, 0) // P

    @pl.when((j > 0) & (j - 1 <= last_page))
    def _page_block():
        jb = j - 1
        # ONE block-diagonal dot for ALL heads over the page (the
        # decode_attention trick at page granularity): q [Hq, D]
        # against the whole [Hkv·P, D] page computes every cross-head
        # product, the mask kills the wrong-head logits exactly.
        q = q_ref[0]                                # [Hq, D], model dtype
        Hq, D = q.shape
        cdt = q.dtype if kp_ref.dtype == jnp.int8 else kp_ref.dtype
        if q.dtype != cdt:
            q = q.astype(cdt)
        kb = kp_ref[0, 0]                           # [Hkv, P, D]
        if kb.dtype != cdt:
            kb = kb.astype(cdt)
        kb = kb.reshape(Hkv * P, D)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [Hq, Hkv·P]
        if quantized:
            # per-position scale folds into the logit plane (per column)
            s = s * ks_ref[0, 0].reshape(1, Hkv * P)
        row_h = jax.lax.broadcasted_iota(
            jnp.int32, (Hq, Hkv * P), 0) // G
        col = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv * P), 1)
        pos = jb * P + col % P       # paged_gather's view coordinate
        valid = (row_h == col // P) & (pos < idx)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                      # [Hq, Hkv·P]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        if quantized:
            # v scale folds into the prob plane
            p = p * vs_ref[0, 0].reshape(1, Hkv * P)
        vb = vp_ref[0, 0]
        if vb.dtype != cdt:
            vb = vb.astype(cdt)
        pv = jax.lax.dot_general(
            p.astype(cdt), vb.reshape(Hkv * P, D),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [Hq, D]
        acc_ref[:, :] = acc_ref[:, :] * alpha + pv

    @pl.when(j == M)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:, :] / jnp.where(l == 0.0, 1.0, l)).astype(
            out_dtype)


def raw_call(sp, q2, kn2, vn2, *pool, scale: float):
    """The pallas_call on local shapes: sp int32 [B, 2 + M] rows of
    ``[layer, index, table...]``; q2 [B, Hq, D]; kn2/vn2 [B, Hkv, D];
    ``pool`` the paged leaves. Returns [B, Hq, D]."""
    B, Hq, D = q2.shape
    Hkv = kn2.shape[1]
    G = Hq // Hkv
    quantized = len(pool) == 4
    kp, vp = pool[0], pool[1]
    P = kp.shape[3]
    M = sp.shape[1] - 2

    def page_map(b, j, sp_ref):
        # THE point of this kernel: the block's pool coordinate is read
        # straight out of the slot's page-table row. Steps past the
        # filled prefix clamp to the last live page (repeated DMA
        # elided), mirroring the stacked kernel's fill clamp.
        last = jnp.maximum(sp_ref[b, 1] - 1, 0) // P
        jp = jnp.minimum(jnp.maximum(j - 1, 0), last)
        return (sp_ref[b, 2 + jp], sp_ref[b, 0], 0, 0, 0)

    def scale_map(b, j, sp_ref):
        last = jnp.maximum(sp_ref[b, 1] - 1, 0) // P
        jp = jnp.minimum(jnp.maximum(j - 1, 0), last)
        return (sp_ref[b, 2 + jp], sp_ref[b, 0], 0, 0)

    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, j, s: (b, 0, 0)),
        pl.BlockSpec((1, 1, Hkv, P, D), page_map),
        pl.BlockSpec((1, 1, Hkv, P, D), page_map),
    ]
    args = [q2, kn2, vn2, kp, vp]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, Hkv, P), scale_map),
                     pl.BlockSpec((1, 1, Hkv, P), scale_map)]
        args += [pool[2], pool[3]]

    kernel = functools.partial(
        _kernel, scale=scale, P=P, M=M, G=G, Hkv=Hkv,
        quantized=quantized, out_dtype=q2.dtype)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, M + 1),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, Hq, D), lambda b, j, s: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hq, D), jnp.float32),
                pltpu.VMEM((Hq, LANES), jnp.float32),
                pltpu.VMEM((Hq, LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q2.dtype),
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(sp, *args)


def paged_reference(q, k_new, v_new, pool, table, layer, index, *,
                    scale: float):
    """The gather+einsum semantics the kernel must match, and the
    off-TPU fallback arm: ``paged_gather`` the slot's pages at
    ``layer``, dequantize, mask positions ``>= index``, softmax over
    [cache, fresh] in f32, combine. Shapes as
    :func:`paged_decode_attention`."""
    B, T, Hq, D = q.shape
    Hkv = k_new.shape[1]
    G = Hq // Hkv
    quantized = len(pool) == 4
    P = pool[0].shape[3]
    M = table.shape[1]

    def one(qb, knb, vnb, row, idx):
        # paged_gather, restricted to one layer: [Hkv, M·P, D]
        def view(leaf):
            g = leaf[row, layer]                  # [M, Hkv, P, *rest]
            g = jnp.moveaxis(g, 0, 1)             # [Hkv, M, P, *rest]
            s = g.shape
            return g.reshape(s[0], s[1] * s[2], *s[3:])
        k_c, v_c = view(pool[0]), view(pool[1])
        if quantized:
            k_c = k_c.astype(qb.dtype) * view(pool[2])[..., None]
            v_c = v_c.astype(qb.dtype) * view(pool[3])[..., None]
        qh = qb.reshape(Hkv, G, D)                # [Hkv, G, D]
        s_c = jnp.einsum("hgd,hsd->hgs", qh, k_c) * scale
        mask = jnp.arange(M * P) < idx
        s_c = jnp.where(mask[None, None, :], s_c, NEG_INF)
        s_n = jnp.sum(qh * knb[:, None, :], axis=-1,
                      keepdims=True) * scale      # [Hkv, G, 1]
        s_all = jnp.concatenate([s_c, s_n], axis=-1).astype(jnp.float32)
        p = jax.nn.softmax(s_all, axis=-1).astype(qb.dtype)
        o = (jnp.einsum("hgs,hsd->hgd", p[..., :-1], v_c)
             + p[..., -1:] * vnb[:, None, :])
        return o.reshape(Hq, D)

    q2 = q.reshape(B, Hq, D)
    kn2 = k_new.reshape(B, Hkv, D)
    vn2 = v_new.reshape(B, Hkv, D)
    out = jax.vmap(one)(q2, kn2, vn2, table,
                        jnp.broadcast_to(jnp.asarray(index, jnp.int32),
                                         (B,)))
    return out.reshape(B, 1, Hq, D)


def paged_decode_attention(q, k_new, v_new, pool, table, layer, index, *,
                           scale: float):
    """q [B, 1, Hq, D]; k_new/v_new [B, Hkv, 1, D] (this step's raw
    k/v, not yet in the pool); ``pool`` the paged leaves; ``table``
    [B, M] int32 per-slot page rows (the engine's device-resident
    table); ``layer`` this block's layer id; ``index`` int32 fill
    position(s) — scalar or [B] (each slot's pool pages hold tokens
    [0, index)). Returns [B, 1, Hq, D]. Dispatches the kernel when
    :func:`supported`, else :func:`paged_reference`."""
    if not supported(q, pool, table):
        return paged_reference(q, k_new, v_new, pool, table, layer,
                               index, scale=scale)
    B, T, Hq, D = q.shape
    Hkv = k_new.shape[1]
    q2 = q.reshape(B, Hq, D)
    kn2 = k_new.reshape(B, Hkv, D)
    vn2 = v_new.reshape(B, Hkv, D)
    idx = jnp.broadcast_to(jnp.asarray(index, jnp.int32), (B,))
    lay = jnp.broadcast_to(jnp.asarray(layer, jnp.int32), (B,))
    sp = jnp.concatenate([lay[:, None], idx[:, None],
                          jnp.asarray(table, jnp.int32)], axis=1)
    out = raw_call(sp, q2, kn2, vn2, *pool, scale=scale)
    return out.reshape(B, 1, Hq, D)
