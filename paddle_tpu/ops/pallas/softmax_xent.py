"""Fused softmax cross-entropy over [N, V] — the LM-head loss.

Reference CUDA equivalents: ``paddle/fluid/operators/
softmax_with_cross_entropy_op.cu`` and ``operators/math/softmax.cu``.
The fused formulation never stores the [N, V] probability matrix:

- forward: a Pallas kernel streams vocab blocks through VMEM computing
  the row log-sum-exp online; the label logit is a cheap gather outside.
- backward: ``softmax = exp(x - lse)`` is recomputed blockwise in a
  second kernel (saving only ``lse`` [N] as residual instead of the
  [N, V] probabilities jax.nn.log_softmax would keep), and the one-hot
  subtraction is a scatter-add outside.

Alignment: row blocks of 128 × vocab blocks of 256 → requires
``N % 128 == 0`` and ``V % 256 == 0`` (Llama's 32000 qualifies); callers
fall back to the jnp path otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_BLOCK_N = 128
_BLOCK_V = 256
_NEG_INF = -1e30


def supported(logits, labels) -> bool:
    if logits.ndim != 2 or labels.ndim != 1:
        return False
    n, v = logits.shape
    if labels.shape[0] != n:
        return False
    # n must tile by the row block (128, or n itself when n < 128 and a
    # multiple of 8); v must tile by the vocab block
    if n % _row_block(n) or n % 8 or v % _BLOCK_V:
        return False
    return logits.dtype in (jnp.float32, jnp.bfloat16)


def _row_block(n: int) -> int:
    return min(_BLOCK_N, n)


def _lse_kernel(x_ref, lse_ref, m_ref, l_ref, *, nv):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    x = x_ref[...].astype(jnp.float32)
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    l_ref[:, :1] = (l_ref[:, :1] * jnp.exp(m_prev - m_new)
                    + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True))
    m_ref[:, :1] = m_new

    @pl.when(iv == nv - 1)
    def _():
        lse = m_ref[:, :1] + jnp.log(l_ref[:, :1])
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _dx_kernel(x_ref, lse_ref, g_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    lse = lse_ref[:, :1]
    g = g_ref[:, :1]
    dx_ref[...] = (jnp.exp(x - lse) * g).astype(dx_ref.dtype)


def _lse(logits):
    n, v = logits.shape
    br = _row_block(n)
    nb, nv = n // br, v // _BLOCK_V
    lse = pl.pallas_call(
        functools.partial(_lse_kernel, nv=nv),
        grid=(nb, nv),
        in_specs=[pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(logits)
    return lse[:, 0]


@jax.custom_vjp
def softmax_cross_entropy(logits, labels):
    """Per-row loss ``lse(logits) - logits[labels]`` for [N, V] logits and
    int [N] labels. ``supported(logits, labels)`` must hold."""
    lse = _lse(logits)
    sel = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse - sel.astype(jnp.float32)


def _sce_fwd(logits, labels):
    lse = _lse(logits)
    sel = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse - sel.astype(jnp.float32), (logits, labels, lse)


def _sce_bwd(res, g):
    logits, labels, lse = res
    n, v = logits.shape
    br = _row_block(n)
    nb, nv = n // br, v // _BLOCK_V
    g = g.astype(jnp.float32)
    dx = pl.pallas_call(
        _dx_kernel,
        grid=(nb, nv),
        in_specs=[
            pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=_support.interpret(),
    )(logits, jnp.broadcast_to(lse[:, None], (n, 128)),
      jnp.broadcast_to(g[:, None], (n, 128)))
    # one-hot subtraction: dx[i, labels[i]] -= g[i]
    dx = dx.at[jnp.arange(n), labels].add((-g).astype(dx.dtype))
    return dx, jnp.zeros(labels.shape, dtype=jax.dtypes.float0)


softmax_cross_entropy.defvjp(_sce_fwd, _sce_bwd)
