"""Fused softmax cross-entropy over [N, V] — the LM-head loss.

Reference CUDA equivalents: ``paddle/fluid/operators/
softmax_with_cross_entropy_op.cu`` and ``operators/math/softmax.cu``.
The fused formulation never stores the [N, V] probability matrix:

- forward: a Pallas kernel streams vocab blocks through VMEM computing
  the row log-sum-exp online; the label logit is a cheap gather outside.
- backward: ``softmax = exp(x - lse)`` is recomputed blockwise in a
  second kernel (saving only ``lse`` [N] as residual instead of the
  [N, V] probabilities jax.nn.log_softmax would keep), and the one-hot
  subtraction is a scatter-add outside.

Alignment: row blocks of 128 × vocab blocks of 256 → requires
``N % 128 == 0`` and ``V % 256 == 0`` (Llama's 32000 qualifies); callers
fall back to the jnp path otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import _support

_BLOCK_N = 128
_BLOCK_V = 256
_NEG_INF = -1e30

# Auto-dispatch ceiling on the vocab width. Measured on a v5e chip
# (fwd+bwd, N=8192, bf16): the kernel wins below ~2k classes
# (V=1024: 4.8ms vs XLA 6.8ms) and loses above (V=4096: 6.7 vs 5.6;
# V=50304: 22.8 vs 11.6 — XLA fuses log_softmax into the surrounding
# graph and reads bf16, while this kernel re-reads the logits for lse
# and dx). LM-head losses must therefore stay on the XLA path; callers
# can still invoke the kernel explicitly for any supported shape.
DISPATCH_MAX_V = 2048


def supported(logits, labels) -> bool:
    if logits.ndim != 2 or labels.ndim != 1:
        return False
    n, v = logits.shape
    if labels.shape[0] != n:
        return False
    # n must tile by the row block (128, or n itself when n < 128 and a
    # multiple of 8); v must tile by the vocab block
    if n % _row_block(n) or n % 8 or v % _BLOCK_V:
        return False
    return logits.dtype in (jnp.float32, jnp.bfloat16)


def _row_block(n: int) -> int:
    return min(_BLOCK_N, n)


def _lse_kernel(x_ref, lse_ref, m_ref, l_ref, *, nv):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    x = x_ref[...].astype(jnp.float32)
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    l_ref[:, :1] = (l_ref[:, :1] * jnp.exp(m_prev - m_new)
                    + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True))
    m_ref[:, :1] = m_new

    @pl.when(iv == nv - 1)
    def _():
        lse = m_ref[:, :1] + jnp.log(l_ref[:, :1])
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _dx_kernel(x_ref, lse_ref, g_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    lse = lse_ref[:, :1]
    g = g_ref[:, :1]
    dx_ref[...] = (jnp.exp(x - lse) * g).astype(dx_ref.dtype)


def _lse_call(logits):
    """Raw kernel: lane-replicated [n, 128] log-sum-exp."""
    n, v = logits.shape
    br = _row_block(n)
    nb, nv = n // br, v // _BLOCK_V
    lse = pl.pallas_call(
        functools.partial(_lse_kernel, nv=nv),
        grid=(nb, nv),
        in_specs=[pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
        ],
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_support.interpret(),
    )(logits)
    return lse


def _dx_call(logits, lse_b, g_b):
    """Raw kernel: softmax(logits)·g from lane-replicated lse/g."""
    n, v = logits.shape
    br = _row_block(n)
    nb, nv = n // br, v // _BLOCK_V
    return pl.pallas_call(
        _dx_kernel,
        grid=(nb, nv),
        in_specs=[
            pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, _BLOCK_V), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
        compiler_params=_support.compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=_support.interpret(),
    )(logits, lse_b, g_b)


def _lse_dispatch(logits, part):
    if part:
        from paddle_tpu.ops.pallas import _partition
        return _partition.xent_lse()(logits)[:, 0]
    return _lse_call(logits)[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _sce(part, logits, labels):
    lse = _lse_dispatch(logits, part)
    sel = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse - sel.astype(jnp.float32)


def _sce_fwd(part, logits, labels):
    lse = _lse_dispatch(logits, part)
    sel = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse - sel.astype(jnp.float32), (logits, labels, lse)


def _sce_bwd(part, res, g):
    logits, labels, lse = res
    n, v = logits.shape
    g = g.astype(jnp.float32)
    lse_b = jnp.broadcast_to(lse[:, None], (n, 128))
    g_b = jnp.broadcast_to(g[:, None], (n, 128))
    if part:
        from paddle_tpu.ops.pallas import _partition
        dx = _partition.xent_dx()(logits, lse_b, g_b)
    else:
        dx = _dx_call(logits, lse_b, g_b)
    # one-hot subtraction: dx[i, labels[i]] -= g[i]
    dx = dx.at[jnp.arange(n), labels].add((-g).astype(dx.dtype))
    return dx, jnp.zeros(labels.shape, dtype=jax.dtypes.float0)


_sce.defvjp(_sce_fwd, _sce_bwd)


def softmax_cross_entropy(logits, labels, *, partitioned: bool = False):
    """Per-row loss ``lse(logits) - logits[labels]`` for [N, V] logits and
    int [N] labels. ``supported(logits, labels)`` must hold.
    ``partitioned`` routes the kernels through custom_partitioning so they
    run per-shard under a multi-device mesh (including a Megatron-style
    vocab-sharded lm head: local lse + log-sum-exp combine over the vocab
    axes)."""
    return _sce(bool(partitioned), logits, labels)
