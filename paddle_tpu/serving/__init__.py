"""paddle_tpu.serving — serving at scale: cross-request dynamic batching,
health-aware replica routing, continuous-batching generation, and the
fleet control plane.

Reference role: the Paddle Serving deployment tier around the inference
engine — a fleet of ``AnalysisPredictor`` replicas behind a router
(``inference/api/analysis_predictor.h:82``, ``inference/capi/
pd_predictor.cc``). TPU-native formulation: the **server half**
(:class:`~paddle_tpu.serving.batcher.DynamicBatcher`, wired into
``io.InferenceServer``) coalesces concurrent ``infer`` requests for the
same model into one bucketed ``Predictor.run`` — the Orca/Clipper-style
micro-batching a TPU wants; the **client half**
(:class:`~paddle_tpu.serving.router.RoutedClient`) spreads idempotent
requests across N replicas by least-inflight pick with health-probe
membership and shed/connect failover, so a replica kill degrades to the
survivors instead of failing callers; and the **control plane**
(:class:`~paddle_tpu.serving.control.ServingController`) is the
fleet-manager role above both — multi-model multiplexing with warm/cold
tiers and LRU eviction, SLO-driven autoscaling from the merged health
signals, and sticky-drain scale-down that never loses an in-flight
generation.
"""

from paddle_tpu.serving.batcher import DynamicBatcher
from paddle_tpu.serving.control import (
    ControlDecision, InProcSpawner, ReplicaSpawner, ServingController,
    SubprocessSpawner,
)
from paddle_tpu.serving.engine import (
    EngineOverloaded, Generation, GenerationEngine, GenerationExpired,
    RequestQuarantined,
)
from paddle_tpu.serving.ha import (
    ControlService, FencedSpawner, FleetJournal, FleetState, LeaderLease,
    StaleEpochError, control_dump,
)
from paddle_tpu.serving.layout import DeviceLayout
from paddle_tpu.serving.ledger import GoodputMeter, RequestLedger, TenantBook
from paddle_tpu.serving.metrics import MetricsHub, hist_delta
from paddle_tpu.serving.router import (
    GenerationFailed, ReplicaState, RoutedClient, StickySession,
    StreamResumeExhausted,
)
from paddle_tpu.serving.sparse import EmbeddingServingTier, SparseCTRPredictor

__all__ = ["DynamicBatcher", "RoutedClient", "ReplicaState",
           "GenerationEngine", "Generation", "EngineOverloaded",
           "StickySession", "GenerationFailed", "ServingController",
           "ControlDecision", "ReplicaSpawner", "InProcSpawner",
           "SubprocessSpawner", "RequestQuarantined", "GenerationExpired",
           "StreamResumeExhausted", "MetricsHub", "hist_delta",
           "DeviceLayout", "RequestLedger", "GoodputMeter", "TenantBook",
           "LeaderLease", "FleetJournal", "FleetState", "FencedSpawner",
           "StaleEpochError", "ControlService", "control_dump",
           "EmbeddingServingTier", "SparseCTRPredictor"]
