"""Continuous-batching generation engine: slot-based KV-cache scheduling
with streaming token delivery.

Reference role: the serving story the reference never had for its decode
loops (``operators/beam_search_op.cc`` + the dygraph sampling loops run
one request to completion, so a long generation starves every other
caller). This module applies iteration-level scheduling (Orca, OSDI '22)
and slot-based KV-cache management (the fixed-slot precursor of vLLM's
paged cache, SOSP '23) to the framework's autoregressive path:

- **One fixed-shape batched cache.** The engine owns ``slots`` KV caches
  of ``max_len`` positions each, allocated once (leaves
  ``[slots, L, 1, Hkv, S, D]``). Shapes never depend on the request mix,
  so XLA compiles exactly one decode step and one prefill per prompt
  bucket — no recompiles as traffic changes.
- **Iteration-level scheduling.** A background loop admits queued
  prompts into free slots (bucketed prefill), steps *all* active slots
  through ONE fused decode (``jax.vmap`` over
  ``model.forward_with_cache`` with per-slot positions — the einsum
  decode path batches exactly), and retires slots on EOS,
  ``max_new_tokens``, cancel, or poll-TTL expiry (client disconnect).
  A request admitted mid-flight shares the very next decode step with
  the requests already running.
- **Host-side request state, device-side cache.** Per-slot prompt
  length, position, RNG key, and sampling params ride the jitted state;
  emitted tokens stream into host buffers that :meth:`~GenerationEngine.
  poll` drains incrementally (the wire ops ``generate_start`` /
  ``generate_poll`` / ``generate_cancel`` in ``io/serving.py``).

Determinism: a greedy (``temperature=0``) generation through the engine
is byte-identical to a solo :func:`paddle_tpu.models.generation.generate`
call — right-padded bucketed prefill and co-tenant slots cannot change a
row's logits (causal masking; row-independent compute). Sampled requests
are deterministic per ``(prompt, seed)`` — each slot splits its own key
once per emitted token — but follow a different key schedule than solo
``generate``.

Observability: ``gen/slots_active`` / ``gen/queue_depth`` gauges,
``gen/prefill_s`` / ``gen/decode_step_s`` histograms, ``gen/tokens`` /
``gen/evictions`` counters, ``gen/prefill`` + ``gen/decode_step`` spans,
and slot occupancy in the serving ``health`` op.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Any

import numpy as np

from paddle_tpu.core import trace as _trace
from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import observe, stat_add, stat_set

__all__ = ["GenerationEngine", "Generation", "EngineOverloaded"]

_UNSET = object()


class EngineOverloaded(RuntimeError):
    """Every slot is busy and the admit queue is full; the request was
    NOT enqueued. Safe to retry after ``retry_after_s`` — the serving
    layer maps this to the wire's retryable ``CODE_SHED`` status."""

    def __init__(self, msg: str, retry_after_s: float = 0.25):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class Generation:
    """Host-side record of one generation request (the engine's unit of
    scheduling). ``tokens`` grows as decode steps emit; ``slot`` is None
    while queued and again after retirement."""

    __slots__ = ("gen_id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "top_p", "eos_token_id", "seed", "tokens",
                 "done", "error", "slot", "created", "last_poll",
                 "cancelled")

    def __init__(self, gen_id: str, prompt: np.ndarray,
                 max_new_tokens: int, temperature: float, top_k: int,
                 top_p: float, eos_token_id: int | None, seed: int):
        self.gen_id = gen_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_token_id = eos_token_id
        self.seed = seed
        self.tokens: list[int] = []
        self.done = False
        self.error: str | None = None
        self.slot: int | None = None
        self.created = time.monotonic()
        self.last_poll = self.created
        self.cancelled = False


def _sample_slot(logits, key, temperature, top_k, top_p):
    """Per-slot next-token pick with fully-traced sampling params (one
    compiled step serves every request mix): greedy argmax where
    ``temperature <= 0`` — bit-equal to ``sample_logits``'s greedy path —
    else temperature / top-k / nucleus sampling with traced ``top_k``
    (``<= 0`` keeps all) and ``top_p`` (``1.0`` keeps all)."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    lt = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    # top-k via the kth-largest threshold, k traced (take clamps indices)
    asc = jnp.sort(lt, axis=-1)
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    kth = jnp.take(asc, V - k_eff)
    lt = jnp.where(lt < kth, -jnp.inf, lt)
    # nucleus over what survived top-k (the sample_logits ordering)
    desc = jnp.sort(lt, axis=-1)[::-1]
    probs = jax.nn.softmax(desc)
    cum = jnp.cumsum(probs)
    keep = cum - probs < top_p              # always keeps the top-1
    thr = jnp.min(jnp.where(keep, desc, jnp.inf))
    lt = jnp.where(lt < thr, -jnp.inf, lt)
    sampled = jax.random.categorical(key, lt).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


class GenerationEngine:
    """Slot-scheduled continuous-batching decode over one model.

    ``model`` is any object with ``init_cache(B, S, dtype=...)`` and
    ``forward_with_cache(ids, cache, index)`` (the ``models/generation``
    contract — Llama/GPT/MoE). ``slots`` defaults to ``FLAGS_gen_slots``
    (0 = generation serving disabled: constructing without an explicit
    ``slots`` raises); ``max_len``/``queue_max``/``ttl_s`` default to
    ``FLAGS_gen_max_len``/``FLAGS_gen_queue_max``/``FLAGS_gen_poll_ttl_s``.

    The background loop starts on construction; :meth:`close` retires it.
    All device state is touched only by the loop thread — the public
    surface (:meth:`start`/:meth:`poll`/:meth:`cancel`) is host-side and
    lock-guarded.
    """

    def __init__(self, model, *, slots: int | None = None,
                 max_len: int | None = None, queue_max: int | None = None,
                 ttl_s: float | None = None, eos_token_id: int | None = None,
                 pad_token_id: int = 0, cache_dtype=None,
                 min_bucket: int = 8, step_wait_s: float = 0.0):
        import jax.numpy as jnp

        if slots is None:
            slots = int(flag("gen_slots"))
        if slots <= 0:
            raise ValueError(
                "generation serving is disabled (FLAGS_gen_slots=0); set "
                "the flag or pass slots= explicitly")
        self.slots = int(slots)
        self.max_len = int(flag("gen_max_len") if max_len is None
                           else max_len)
        cfg_max = getattr(getattr(model, "config", None), "max_seq_len",
                          None)
        if cfg_max is not None:
            self.max_len = min(self.max_len, int(cfg_max))
        self._queue_max = int(flag("gen_queue_max") if queue_max is None
                              else queue_max)
        self._ttl_s = float(flag("gen_poll_ttl_s") if ttl_s is None
                            else ttl_s)
        self._eos_default = eos_token_id
        self._pad = int(pad_token_id)
        self._min_bucket = max(int(min_bucket), 1)
        # pacing knob: minimum gap between fused decode steps (throttle
        # a host-loop-bound engine, or make scheduling windows
        # deterministic in tests/chaos checks); 0 = run flat out
        self.step_wait_s = float(step_wait_s)
        self._model = model
        self._cache_dtype = cache_dtype

        proto = model.init_cache(1, self.max_len, dtype=cache_dtype)
        import jax

        self._state: dict[str, Any] = {
            "cache": jax.tree_util.tree_map(
                lambda x: jnp.zeros((self.slots,) + x.shape, x.dtype),
                proto),
            "tok": jnp.zeros((self.slots,), jnp.int32),
            "pos": jnp.zeros((self.slots,), jnp.int32),
            "keys": jnp.zeros((self.slots, 2), jnp.uint32),
            "temp": jnp.zeros((self.slots,), jnp.float32),
            "top_k": jnp.zeros((self.slots,), jnp.int32),
            "top_p": jnp.ones((self.slots,), jnp.float32),
        }
        self._step = self._build_step()
        self._prefill_fn = self._build_prefill()

        self._cond = threading.Condition()
        self._queue: deque[Generation] = deque()
        self._slot_gen: list[Generation | None] = [None] * self.slots
        self._gens: dict[str, Generation] = {}
        self._stopping = False
        self._broken: str | None = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gen-engine")
        self._thread.start()

    # -- compiled pieces ---------------------------------------------------
    def _build_step(self):
        """ONE fused decode for all slots: vmap the model's single-token
        cached forward over the slot axis with per-slot positions/keys/
        sampling params. Inactive slots compute too (fixed cost, fixed
        shapes) but their token/position state is frozen by the mask and
        their cache garbage is overwritten at the next admit."""
        import jax
        import jax.numpy as jnp

        model = self._model

        def one(cache, tok, idx, key, temp, top_k, top_p):
            logits, cache = model.forward_with_cache(
                tok[None, None], cache, index=idx)
            key, sub = jax.random.split(key)
            nxt = _sample_slot(logits[0, -1], sub, temp, top_k, top_p)
            return cache, nxt, key

        def step(state, active):
            cache, nxt, keys = jax.vmap(one)(
                state["cache"], state["tok"], state["pos"], state["keys"],
                state["temp"], state["top_k"], state["top_p"])
            tok = jnp.where(active, nxt, state["tok"])
            pos = state["pos"] + active.astype(jnp.int32)
            return dict(state, cache=cache, tok=tok, pos=pos,
                        keys=keys), tok

        return jax.jit(step, donate_argnums=(0,))

    def _build_prefill(self):
        """Prefill one slot from a right-padded prompt bucket (compiled
        once per bucket length; ``slot``/``true_len`` are traced). The
        whole slot cache is overwritten, so stale state from the previous
        occupant never leaks into the new generation."""
        import jax
        import jax.numpy as jnp

        model, S, cache_dtype = self._model, self.max_len, self._cache_dtype

        def prefill(state, slot, padded, true_len, key, temp, top_k, top_p):
            b1 = model.init_cache(1, S, dtype=cache_dtype)
            logits, b1 = model.forward_with_cache(padded[None], b1,
                                                  index=0)
            key, sub = jax.random.split(key)
            tok0 = _sample_slot(logits[0, true_len - 1], sub, temp, top_k,
                                top_p)
            cache = jax.tree_util.tree_map(
                lambda big, sm: big.at[slot].set(sm), state["cache"], b1)
            return dict(
                cache=cache,
                tok=state["tok"].at[slot].set(tok0),
                pos=state["pos"].at[slot].set(true_len),
                keys=state["keys"].at[slot].set(key),
                temp=state["temp"].at[slot].set(temp),
                top_k=state["top_k"].at[slot].set(jnp.asarray(top_k,
                                                              jnp.int32)),
                top_p=state["top_p"].at[slot].set(top_p),
            ), tok0

        return jax.jit(prefill, donate_argnums=(0,))

    def _bucket(self, n: int) -> int:
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_len)

    # -- public surface ----------------------------------------------------
    def start(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
              top_k: int = 0, top_p: float = 1.0, eos_token_id=_UNSET,
              seed: int = 0) -> str:
        """Enqueue a generation; returns its id immediately. Raises
        :class:`EngineOverloaded` (retryable) when every slot is busy and
        the admit queue is at ``queue_max``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's per-slot "
                f"capacity ({self.max_len}); raise FLAGS_gen_max_len")
        eos = self._eos_default if eos_token_id is _UNSET else eos_token_id
        gen = Generation(uuid.uuid4().hex[:16], prompt, max_new_tokens,
                         float(temperature), int(top_k), float(top_p),
                         None if eos is None else int(eos), int(seed))
        with self._cond:
            if self._stopping:
                raise RuntimeError("GenerationEngine is stopped")
            if self._broken is not None:
                raise RuntimeError(
                    f"GenerationEngine is broken: {self._broken}")
            free = sum(g is None for g in self._slot_gen)
            if (self._queue_max > 0
                    and len(self._queue) - free >= self._queue_max):
                stat_add("gen/shed")
                raise EngineOverloaded(
                    f"engine full: {self.slots} slots busy, "
                    f"{len(self._queue)} queued (queue_max="
                    f"{self._queue_max})")
            self._queue.append(gen)
            self._gens[gen.gen_id] = gen
            stat_set("gen/queue_depth", len(self._queue))
            self._cond.notify_all()
        return gen.gen_id

    def poll(self, gen_id: str, start: int = 0,
             wait_s: float = 0.0) -> dict:
        """Drain tokens past ``start``; blocks up to ``wait_s`` for new
        ones (long-poll). Returns ``{"tokens", "done", "error",
        "queued"}``. Polling refreshes the generation's TTL — a client
        that stops polling for ``ttl_s`` is treated as disconnected and
        its slot reclaimed."""
        start = max(int(start), 0)
        deadline = time.monotonic() + max(float(wait_s), 0.0)
        with self._cond:
            gen = self._gens.get(gen_id)
            if gen is None:
                raise KeyError(f"unknown generation {gen_id!r} "
                               "(finished long ago, evicted, or never "
                               "started here)")
            gen.last_poll = time.monotonic()
            while (not gen.done and len(gen.tokens) <= start
                   and not self._stopping):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                gen.last_poll = time.monotonic()
            return {"tokens": list(gen.tokens[start:]), "done": gen.done,
                    "error": gen.error,
                    "queued": gen.slot is None and not gen.done}

    def cancel(self, gen_id: str) -> bool:
        """Cancel a generation and free its slot (idempotent; unknown
        ids return False). A freed slot is eligible for the very next
        admit."""
        with self._cond:
            gen = self._gens.pop(gen_id, None)
            if gen is None:
                return False
            gen.cancelled = True
            if not gen.done:
                gen.done = True
                gen.error = gen.error or "cancelled"
                self._release_slot_locked(gen, evicted=True)
                try:
                    self._queue.remove(gen)
                except ValueError:
                    pass
                stat_set("gen/queue_depth", len(self._queue))
            self._cond.notify_all()
        return True

    def stats(self) -> dict:
        """Slot occupancy snapshot (shipped in the serving ``health``
        op)."""
        with self._cond:
            active = sum(g is not None for g in self._slot_gen)
            return {"slots": self.slots, "active": active,
                    "free": self.slots - active,
                    "queued": len(self._queue),
                    "generations": len(self._gens),
                    "max_len": self.max_len,
                    "broken": self._broken}

    def close(self) -> None:
        """Stop the loop; error out queued/active generations."""
        with self._cond:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=10.0)
        with self._cond:
            for gen in list(self._gens.values()):
                if not gen.done:
                    gen.done = True
                    gen.error = gen.error or "engine stopped"
                    gen.slot = None
            self._slot_gen = [None] * self.slots
            self._queue.clear()
            self._cond.notify_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- scheduler loop ----------------------------------------------------
    def _loop(self) -> None:
        import jax.numpy as jnp

        while True:
            with self._cond:
                if self._stopping:
                    return
                if (not self._queue
                        and not any(g is not None for g in self._slot_gen)):
                    # idle: wake on new work, and periodically anyway so
                    # TTL reaping runs while nothing is streaming
                    self._cond.wait(timeout=0.25)
                    if self._stopping:
                        return
            try:
                self._reap_expired()
                self._admit()
                self._decode_step(jnp)
            except Exception as e:   # device-side failure: fail loudly,
                self._break(e)       # refuse new work, keep pollers sane
                return

    def _break(self, e: Exception) -> None:
        msg = f"{type(e).__name__}: {e}"
        with self._cond:
            self._broken = msg
            for gen in list(self._gens.values()):
                if not gen.done:
                    gen.done = True
                    gen.error = msg
                    gen.slot = None
            self._slot_gen = [None] * self.slots
            self._queue.clear()
            self._cond.notify_all()

    def _release_slot_locked(self, gen: Generation,
                             evicted: bool = False) -> None:
        if gen.slot is not None and self._slot_gen[gen.slot] is gen:
            self._slot_gen[gen.slot] = None
            if evicted:
                stat_add("gen/evictions")
        gen.slot = None
        stat_set("gen/slots_active",
                 sum(g is not None for g in self._slot_gen))

    def _reap_expired(self) -> None:
        if self._ttl_s <= 0:
            return
        now = time.monotonic()
        with self._cond:
            expired = [g for g in self._gens.values()
                       if now - max(g.created, g.last_poll) > self._ttl_s]
        for gen in expired:
            with self._cond:
                g = self._gens.pop(gen.gen_id, None)
                if g is None:
                    continue
                if not g.done:
                    g.done = True
                    g.error = "evicted: poll TTL exceeded (client gone?)"
                    self._release_slot_locked(g, evicted=True)
                    try:
                        self._queue.remove(g)
                    except ValueError:
                        pass
                self._cond.notify_all()

    def _admit(self) -> None:
        while True:
            with self._cond:
                free = [s for s, g in enumerate(self._slot_gen)
                        if g is None]
                if not free or not self._queue:
                    stat_set("gen/queue_depth", len(self._queue))
                    return
                gen = self._queue.popleft()
                if gen.done:          # cancelled while queued
                    continue
                slot = free[0]
                self._slot_gen[slot] = gen
                gen.slot = slot
                stat_set("gen/slots_active",
                         sum(g is not None for g in self._slot_gen))
            self._prefill(gen, slot)

    def _prefill(self, gen: Generation, slot: int) -> None:
        import jax
        import jax.numpy as jnp

        T0 = gen.prompt.size
        bucket = self._bucket(T0)
        padded = np.full((bucket,), self._pad, np.int32)
        padded[:T0] = gen.prompt
        key = jax.random.PRNGKey(gen.seed)
        t0 = time.perf_counter()
        with _trace.span("gen/prefill", slot=slot, prompt_len=T0,
                         bucket=bucket):
            self._state, tok0 = self._prefill_fn(
                self._state, jnp.asarray(slot, jnp.int32),
                jnp.asarray(padded), jnp.asarray(T0, jnp.int32), key,
                jnp.asarray(gen.temperature, jnp.float32),
                jnp.asarray(gen.top_k, jnp.int32),
                jnp.asarray(gen.top_p, jnp.float32))
            tok0 = int(tok0)
        observe("gen/prefill_s", time.perf_counter() - t0)
        with self._cond:
            if self._slot_gen[slot] is not gen:   # cancelled mid-prefill
                return
            gen.tokens.append(tok0)
            stat_add("gen/tokens")
            if ((gen.eos_token_id is not None
                 and tok0 == gen.eos_token_id)
                    or len(gen.tokens) >= gen.max_new_tokens):
                gen.done = True
                self._release_slot_locked(gen)
            self._cond.notify_all()

    def _decode_step(self, jnp) -> None:
        with self._cond:
            stepped = [(s, g) for s, g in enumerate(self._slot_gen)
                       if g is not None]
            if not stepped:
                return
            active = np.zeros((self.slots,), bool)
            for s, _ in stepped:
                active[s] = True
        t0 = time.perf_counter()
        with _trace.span("gen/decode_step", active=len(stepped)):
            self._state, toks = self._step(self._state,
                                           jnp.asarray(active))
            toks = np.asarray(toks)
        observe("gen/decode_step_s", time.perf_counter() - t0)
        with self._cond:
            emitted = 0
            for s, gen in stepped:
                if self._slot_gen[s] is not gen:   # cancelled mid-step
                    continue
                tok = int(toks[s])
                gen.tokens.append(tok)
                emitted += 1
                if ((gen.eos_token_id is not None
                     and tok == gen.eos_token_id)
                        or len(gen.tokens) >= gen.max_new_tokens):
                    gen.done = True
                    self._release_slot_locked(gen)
            if emitted:
                stat_add("gen/tokens", emitted)
            self._cond.notify_all()
        if self.step_wait_s > 0:
            time.sleep(self.step_wait_s)
