"""Continuous-batching generation engine: slot-based KV-cache scheduling
with streaming token delivery.

Reference role: the serving story the reference never had for its decode
loops (``operators/beam_search_op.cc`` + the dygraph sampling loops run
one request to completion, so a long generation starves every other
caller). This module applies iteration-level scheduling (Orca, OSDI '22)
and slot-based KV-cache management (the fixed-slot precursor of vLLM's
paged cache, SOSP '23) to the framework's autoregressive path:

- **One fixed-shape batched cache.** The engine owns ``slots`` KV caches
  of ``max_len`` positions each, allocated once (leaves
  ``[slots, L, 1, Hkv, S, D]``). Shapes never depend on the request mix,
  so XLA compiles exactly one decode step and one prefill per prompt
  bucket — no recompiles as traffic changes.
- **Iteration-level scheduling.** A background loop admits queued
  prompts into free slots (bucketed prefill), steps *all* active slots
  through ONE fused decode (``jax.vmap`` over
  ``model.forward_with_cache`` with per-slot positions — the einsum
  decode path batches exactly), and retires slots on EOS,
  ``max_new_tokens``, cancel, or poll-TTL expiry (client disconnect).
  A request admitted mid-flight shares the very next decode step with
  the requests already running.
- **Host-side request state, device-side cache.** Per-slot prompt
  length, position, RNG key, and sampling params ride the jitted state;
  emitted tokens stream into host buffers that :meth:`~GenerationEngine.
  poll` drains incrementally (the wire ops ``generate_start`` /
  ``generate_poll`` / ``generate_cancel`` in ``io/serving.py``).
- **Paged mode** (``FLAGS_gen_paged``, off by default). The contiguous
  per-slot regions above make a 16-token completion pay HBM for
  ``max_len`` positions; paged mode (vLLM PagedAttention, SOSP '23)
  replaces them with a pool of ``FLAGS_gen_pages`` physical pages of
  ``FLAGS_gen_page_tokens`` tokens plus per-slot page tables
  (``models.generation.init_paged_cache`` / ``paged_gather`` /
  ``paged_scatter``). A generation reserves pages for its *declared*
  worst case (prompt + ``max_new_tokens``) at admission — capacity
  becomes ``pool / actual-need`` instead of ``slots`` — and admission
  stalls on page-pool exhaustion, not slot count. A radix prefix cache
  over full prompt pages maps generations sharing a prompt prefix onto
  the same refcounted physical pages, so the shared prefix prefills
  once (``gen/prefix_hits`` / ``gen/prefix_tokens_saved``; cached pages
  are LRU-evicted under pool pressure). Chunked prefill
  (``FLAGS_gen_prefill_chunk``) admits long prompts in token slices
  interleaved with decode steps, so active streams keep emitting
  during a long prefill instead of stalling behind it.
- **Speculative decoding** (``FLAGS_gen_spec_k``, off by default).
  Decode is memory-bandwidth-bound, so the only way past the roofline
  is fewer serial target-model steps: a cheap drafter proposes up to
  ``k`` tokens per slot — the model-free n-gram lookup of
  ``models.generation.ngram_propose`` (``FLAGS_gen_spec_mode=ngram``,
  zero extra weights) or a small draft model sharing the cache
  contract (``mode=draft``, ``draft_model=``) — and ONE fused verify
  forward of the target model over the ``k+1`` proposed positions
  (the multi-token prefill machinery) yields the target's pick at
  every position; the longest matching draft prefix is accepted plus
  the target's own pick at the first mismatch, so a slot emits 1..k+1
  tokens per step and every emitted token is exactly what
  non-speculative decode would produce. Rejected drafts roll back by
  position-pointer arithmetic (contiguous mode: attention masks
  positions at/past the decode index, later writes overwrite them;
  paged mode: rejected in-page offsets are scattered to the null page
  — refcount-safe truncation), and each generation reserves ``k``
  scratch positions past its declared worst case so a full-width
  verify near the end of generation stays in bounds. Speculation is
  per-slot and load-adaptive: the draft budget sheds to 0 above
  ``FLAGS_gen_spec_shed_occupancy`` slot occupancy (batched decode
  already fills the MXU then), and mixed speculating/non-speculating
  slots coexist in one compiled verify call (draft length 0 = a plain
  step for that slot; an all-shed iteration runs the original fused
  step unchanged). One ``key`` split is consumed per EMITTED token
  regardless of acceptance pattern, so sampled streams replay
  identically with speculation on or off and ``rng_skip`` stream
  resumption composes unchanged.

Determinism: a greedy (``temperature=0``) generation through the engine
is byte-identical to a solo :func:`paddle_tpu.models.generation.generate`
call — right-padded bucketed prefill and co-tenant slots cannot change a
row's logits (causal masking; row-independent compute). Sampled requests
are deterministic per ``(prompt, seed)`` — each slot splits its own key
once per emitted token — but follow a different key schedule than solo
``generate``.

Self-healing (``FLAGS_gen_engine_rebuilds`` / ``FLAGS_gen_watchdog_s``
/ ``FLAGS_gen_quarantine_after``, all hard-off): a decode-loop trap no
longer bricks the engine forever — the active generations fail loudly
(their error carries the ``engine reset:`` marker, which the routed
client treats as resumable), the cache pool and slot state are rebuilt,
and work is re-admitted, up to ``gen_engine_rebuilds`` *consecutive*
traps. A watchdog thread detects a stuck decode step (loop heartbeat
older than ``gen_watchdog_s`` with active work), fails the stranded
generations so their clients resume elsewhere, and sheds new starts
until the stuck call returns and the loop rebuilds. Crash quarantine
fingerprints the request under a trap (prompt bytes + sampling params);
a fingerprint that traps ``gen_quarantine_after`` times is rejected at
:meth:`~GenerationEngine.start` with the typed
:class:`RequestQuarantined` instead of being retried into every replica
in the fleet. Fault-injection sites ``engine.prefill`` /
``engine.decode_step`` / ``paged.alloc`` (``core/fault.py``) make every
one of these paths deterministically testable.

Observability: ``gen/slots_active`` / ``gen/queue_depth`` /
``gen/pages_free`` gauges, ``gen/prefill_s`` / ``gen/prefill_chunk_s`` /
``gen/decode_step_s`` / ``gen/ttft_s`` (enqueue → first token — the
autoscaling SLO signal) / ``gen/spec_verify_s`` (the fused verify
forward) / ``gen/spec_accept_len`` (draft tokens accepted per verify)
histograms, ``gen/spec_proposed`` / ``gen/spec_accepted`` /
``gen/spec_rejected`` counters plus per-engine acceptance rate and
``tokens_per_step`` in :meth:`~GenerationEngine.stats` (shipped in the
serving ``health`` op next to slot occupancy, so the controller sees
speculation efficiency), ``gen/tokens`` / ``gen/evictions`` /
``gen/prefix_hits`` / ``gen/prefix_tokens_saved`` /
``gen/prefix_evictions`` / ``gen/traps`` / ``gen/rebuilds`` /
``gen/stuck`` / ``gen/quarantined`` / ``gen/quarantine_rejected`` /
``gen/expired_polls`` counters, ``gen/prefill`` + ``gen/prefill_chunk``
+ ``gen/decode_step`` spans, and slot + page-pool occupancy in the
serving ``health`` op.
"""

from __future__ import annotations

import hashlib
import random as _random_mod
import threading
import time
import uuid
from collections import deque
from typing import Any

import numpy as np

from paddle_tpu.core import fault as _fault
from paddle_tpu.core import trace as _trace
from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import observe, stat_add, stat_set

__all__ = ["GenerationEngine", "Generation", "EngineOverloaded",
           "RequestQuarantined", "GenerationExpired", "RESET_MARKER",
           "QUARANTINE_MARKER", "EXPIRED_MARKER", "stream_fingerprint"]

_UNSET = object()

# Marker prefixes for typed failures as they cross the wire (the frame
# protocol carries error strings; clients re-raise the typed class when
# they see the marker — the io/serving ``ModelBusyError`` pattern).
RESET_MARKER = "engine reset:"          # resumable: slot state lost,
#                                         engine (and replica) still up
QUARANTINE_MARKER = "request quarantined:"   # typed give-up, never retry
EXPIRED_MARKER = "generation expired:"       # poll-TTL reap, not unknown

# private shed-jitter stream: synchronized clients whose starts were all
# shed in the same instant must not re-stampede in the same instant
_jitter_rng = _random_mod.Random()


def _jittered(base: float) -> float:
    """``base`` scaled by U[0.5, 1.5) — the retry hint synchronized
    shed clients back off by must de-synchronize them."""
    return base * (0.5 + _jitter_rng.random())


def stream_fingerprint(prompt, temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, seed: int = 0) -> str:
    """Crash fingerprint of a stream — the quarantine identity. One
    recipe shared by the engine (every :class:`Generation` hashes its
    own request) and the resuming router client (which passes the
    ORIGINAL stream's fingerprint on replay attempts, wire header
    ``fp``, because the replay prompt grew by the delivered tokens and
    would otherwise hash fresh — letting resumed poison dodge
    quarantine)."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    return hashlib.sha1(
        prompt.tobytes()
        + f"|{float(temperature)}|{int(top_k)}|{float(top_p)}"
          f"|{int(seed)}".encode()
    ).hexdigest()[:16]


class EngineOverloaded(RuntimeError):
    """Every slot is busy and the admit queue is full; the request was
    NOT enqueued. Safe to retry after ``retry_after_s`` — the serving
    layer maps this to the wire's retryable ``CODE_SHED`` status."""

    def __init__(self, msg: str, retry_after_s: float = 0.25):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestQuarantined(RuntimeError):
    """This request's crash fingerprint (prompt bytes + sampling
    params) has trapped the engine ``FLAGS_gen_quarantine_after``
    times; it is rejected at admission instead of being retried into
    every replica in the fleet. NOT retryable — the typed give-up the
    stream-resumption layer must surface, never resume past."""

    def __init__(self, msg: str, fingerprint: str = ""):
        super().__init__(msg)
        self.fingerprint = fingerprint


class GenerationExpired(KeyError):
    """The polled generation existed here but was reaped by the poll
    TTL (client presumed disconnected). Distinct from a plain
    ``KeyError`` — "expired" is a fact about THIS replica, "unknown"
    may mean the caller polled the wrong replica entirely."""


class _EpochChanged(RuntimeError):
    """Internal: the watchdog failed this step's generations while the
    compiled call was in flight — its results (and the state it
    returned) must be discarded, and the loop must rebuild or break."""


class Generation:
    """Host-side record of one generation request (the engine's unit of
    scheduling). ``tokens`` grows as decode steps emit; ``slot`` is None
    while queued and again after retirement."""

    __slots__ = ("gen_id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "top_p", "eos_token_id", "seed", "tokens",
                 "done", "error", "slot", "created", "last_poll",
                 "cancelled", "pages", "shared", "prefilling",
                 "prefill_pos", "prefill_t0", "delivered", "fingerprint",
                 "rng_skip", "spec_proposed", "spec_accepted", "trace_id",
                 "tenant", "admitted_ts", "first_tok_ts", "done_ts",
                 "chip_s", "ledgered", "dev_ops", "pclass", "folded",
                 "queue_booked", "sched_seq", "sched_vft", "sched_ts")

    def __init__(self, gen_id: str, prompt: np.ndarray,
                 max_new_tokens: int, temperature: float, top_k: int,
                 top_p: float, eos_token_id: int | None, seed: int):
        self.gen_id = gen_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_token_id = eos_token_id
        self.seed = seed
        self.tokens: list[int] = []
        self.done = False
        self.error: str | None = None
        self.slot: int | None = None
        self.created = time.monotonic()
        self.last_poll = self.created
        self.cancelled = False
        # a poll response carried done=True with every token: the client
        # has everything — the signal a sticky drain waits on
        self.delivered = False
        # paged mode: mapped physical pages (shared prefix first), how
        # many of them are prefix-cache hits, and chunked-prefill cursor
        self.pages: list[int] = []
        self.shared = 0
        self.prefilling = False
        self.prefill_pos = 0
        self.prefill_t0 = 0.0
        # crash fingerprint (quarantine identity) and the RNG position a
        # resumed sampled stream replays (splits consumed before this
        # stream's first token — 0 for a fresh stream)
        self.fingerprint = stream_fingerprint(prompt, temperature,
                                              top_k, top_p, seed)
        self.rng_skip = 0
        # stream trace id (wire header "st"): the fleet-unique identity
        # of the LOGICAL stream this generation serves — minted once at
        # the first generate_start and replayed verbatim by failover
        # resume, so one stream's slot events merge across replicas
        self.trace_id: str | None = None
        # speculative-decoding acceptance accounting (draft tokens this
        # generation proposed / had accepted; stays 0 with spec off)
        self.spec_proposed = 0
        self.spec_accepted = 0
        # latency-ledger books (wire header "tn" + monotonic phase
        # stamps + attributed device seconds); stamps stay 0.0 and
        # ledgered stays False for the engine's whole life when
        # FLAGS_gen_ledger is off
        self.tenant: str | None = None
        self.admitted_ts = 0.0
        self.first_tok_ts = 0.0
        self.done_ts = 0.0
        self.chip_s = 0.0
        self.ledgered = False
        # lazily built device-side per-request operands (starting PRNG
        # key with rng_skip applied, temperature/top_k/top_p scalars) —
        # immutable for the generation's lifetime, so chunked prefill
        # stops re-materializing them every chunk
        self.dev_ops: tuple | None = None
        # scheduler books (FLAGS_gen_sched; inert defaults otherwise):
        # priority class, tokens already folded into the prompt by a
        # preemption park, queue wait booked live at admission, and the
        # fair-queue tag/sequence/admission-stamp the scheduler assigns
        self.pclass = "batch"
        self.folded = 0
        self.queue_booked = 0.0
        self.sched_seq = 0
        self.sched_vft = 0.0
        self.sched_ts = 0.0


class _PagePool:
    """Host-side refcounted allocator over the physical page pool.
    Usable page ids are ``1 .. num_pages``; id 0 is the reserved null
    page (unmapped table entries, masked padding writes). All methods
    run under the engine's condition lock."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages, 0, -1))   # pop() -> 1 first
        self._ref = [0] * (self.num_pages + 1)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        _fault.inject("paged.alloc")
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: need {n}, free {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        for pid in out:
            self._ref[pid] = 1
        return out

    def retain(self, pid: int) -> None:
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            self._free.append(pid)
        elif self._ref[pid] < 0:        # double free = allocator bug
            raise AssertionError(f"page {pid} refcount underflow")

    def refcount(self, pid: int) -> int:
        return self._ref[pid]


class _PrefixEntry:
    __slots__ = ("key", "page", "parent_page", "children", "last_used")

    def __init__(self, key, page: int, parent_page: int):
        self.key = key
        self.page = page
        self.parent_page = parent_page
        self.children = 0
        self.last_used = 0


class _PrefixCache:
    """Radix cache over FULL prompt pages: entry key = (parent page id,
    the page's token bytes), so two prompts share exactly their common
    whole-page prefix. Only pages fully covered by a prompt are ever
    registered (decode writes start at the prompt length — registered
    pages are immutable), and a match is capped so at least one prompt
    token remains to prefill (the sampled first token needs its logits).
    The cache holds its own +1 refcount per registered page, so shared
    pages outlive their last generation until LRU-evicted under pool
    pressure (leaf entries first — a parent is only evictable once its
    children are gone)."""

    def __init__(self, page_tokens: int):
        self._P = int(page_tokens)
        self._entries: dict[tuple, _PrefixEntry] = {}
        self._by_page: dict[int, _PrefixEntry] = {}
        self._clock = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _touch(self, e: _PrefixEntry) -> None:
        self._clock += 1
        e.last_used = self._clock

    def match(self, prompt: np.ndarray, pool: _PagePool) -> list[int]:
        """Longest cached whole-page prefix of ``prompt``; each matched
        page is retained for the caller (release on failure/retire)."""
        P = self._P
        cap = (int(prompt.size) - 1) // P
        pages: list[int] = []
        parent = 0
        for i in range(cap):
            e = self._entries.get((parent, prompt[i * P:(i + 1) * P]
                                   .tobytes()))
            if e is None:
                break
            self._touch(e)
            pool.retain(e.page)
            pages.append(e.page)
            parent = e.page
        return pages

    def insert(self, prompt: np.ndarray, gen_pages: list[int],
               pool: _PagePool) -> None:
        """Register a finished prefill's full prompt pages. Pages whose
        chain key is already cached (matched, or raced by a concurrent
        identical prompt) are touched, not replaced — the generation
        keeps its private copy in that case."""
        P = self._P
        parent = 0
        for i in range(int(prompt.size) // P):
            key = (parent, prompt[i * P:(i + 1) * P].tobytes())
            e = self._entries.get(key)
            if e is None:
                e = _PrefixEntry(key, gen_pages[i], parent_page=parent)
                self._entries[key] = e
                self._by_page[e.page] = e
                pool.retain(e.page)
                pe = self._by_page.get(parent)
                if pe is not None:
                    pe.children += 1
            self._touch(e)
            parent = e.page

    def evict(self, n: int, pool: _PagePool, demote=None) -> int:
        """Free up to ``n`` pages by dropping LRU leaf entries no live
        generation references (page refcount 1 = cache-only). With a
        ``demote`` callback (the KV-store hook), each victim is handed
        over — still registered, page still live — before release, so
        eviction demotes the page to the store instead of dropping it."""
        freed = 0
        while freed < n:
            cands = [e for e in self._entries.values()
                     if e.children == 0 and pool.refcount(e.page) == 1]
            if not cands:
                break
            e = min(cands, key=lambda c: c.last_used)
            if demote is not None:
                demote(e)
            del self._entries[e.key]
            self._by_page.pop(e.page, None)
            pe = self._by_page.get(e.parent_page)
            if pe is not None:
                pe.children -= 1
            pool.release(e.page)
            freed += 1
        if freed:
            stat_add("gen/prefix_evictions", freed)
        return freed

    def chain_tokens(self, e: _PrefixEntry) -> list[bytes] | None:
        """Root-to-leaf token bytes of ``e``'s radix chain (each element
        is one full page's int32 token bytes) — the input to the store's
        :func:`~paddle_tpu.serving.kvstore.page_chain_keys`. A parent is
        only evictable after its children, so the walk is complete for
        any live entry; returns None on a broken chain (mid-rebuild)."""
        chain: list[bytes] = []
        cur = e
        while True:
            chain.append(cur.key[1])
            if cur.parent_page == 0:
                break
            cur = self._by_page.get(cur.parent_page)
            if cur is None:
                return None
        chain.reverse()
        return chain


def _sample_slot(logits, key, temperature, top_k, top_p):
    """Per-slot next-token pick with fully-traced sampling params (one
    compiled step serves every request mix): greedy argmax where
    ``temperature <= 0`` — bit-equal to ``sample_logits``'s greedy path —
    else temperature / top-k / nucleus sampling with traced ``top_k``
    (``<= 0`` keeps all) and ``top_p`` (``1.0`` keeps all)."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    lt = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    # top-k via the kth-largest threshold, k traced (take clamps indices)
    asc = jnp.sort(lt, axis=-1)
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    kth = jnp.take(asc, V - k_eff)
    lt = jnp.where(lt < kth, -jnp.inf, lt)
    # nucleus over what survived top-k (the sample_logits ordering)
    desc = jnp.sort(lt, axis=-1)[::-1]
    probs = jax.nn.softmax(desc)
    cum = jnp.cumsum(probs)
    keep = cum - probs < top_p              # always keeps the top-1
    thr = jnp.min(jnp.where(keep, desc, jnp.inf))
    lt = jnp.where(lt < thr, -jnp.inf, lt)
    sampled = jax.random.categorical(key, lt).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


class GenerationEngine:
    """Slot-scheduled continuous-batching decode over one model.

    ``model`` is any object with ``init_cache(B, S, dtype=...)`` and
    ``forward_with_cache(ids, cache, index)`` (the ``models/generation``
    contract — Llama/GPT/MoE). ``slots`` defaults to ``FLAGS_gen_slots``
    (0 = generation serving disabled: constructing without an explicit
    ``slots`` raises); ``max_len``/``queue_max``/``ttl_s`` default to
    ``FLAGS_gen_max_len``/``FLAGS_gen_queue_max``/``FLAGS_gen_poll_ttl_s``.

    ``paged``/``page_tokens``/``pages``/``prefill_chunk``/``prefix_cache``
    default to the ``FLAGS_gen_paged``/``gen_page_tokens``/``gen_pages``/
    ``gen_prefill_chunk``/``gen_prefix_cache`` flags; with paging off
    (the default) the engine keeps the PR-5 contiguous per-slot cache
    byte-identically. Greedy output is byte-identical to solo
    ``generate()`` in both modes, under any co-tenant mix, page reuse,
    and chunked prefill.

    ``mesh_tp`` defaults to ``FLAGS_gen_mesh_tp`` (0 = no mesh: the
    single-device path, byte-identical to the pre-sharding build). A
    positive degree builds the engine over a tensor-parallel device
    mesh — params column/row-split, KV cache/page pool sharded on the
    KV-head axis, every compiled entry point given explicit in/out
    shardings (``serving/layout.py``). Token streams stay
    byte-identical across layouts, so failover/resume compose with any
    mix of sharded and unsharded replicas; ``stats()['device']`` ships
    the topology.

    ``quarantine_after``/``rebuilds``/``watchdog_s`` default to the
    ``gen_quarantine_after``/``gen_engine_rebuilds``/``gen_watchdog_s``
    flags (all 0 = the pre-resilience behavior: no quarantine books, the
    first decode-loop trap breaks the engine terminally, no watchdog
    thread). See the module docstring's self-healing section.

    The background loop starts on construction; :meth:`close` retires it.
    All device state is touched only by the loop thread — the public
    surface (:meth:`start`/:meth:`poll`/:meth:`cancel`) is host-side and
    lock-guarded.
    """

    def __init__(self, model, *, slots: int | None = None,
                 max_len: int | None = None, queue_max: int | None = None,
                 ttl_s: float | None = None, eos_token_id: int | None = None,
                 pad_token_id: int = 0, cache_dtype=None,
                 min_bucket: int = 8, step_wait_s: float = 0.0,
                 paged: bool | None = None, page_tokens: int | None = None,
                 pages: int | None = None, prefill_chunk: int | None = None,
                 prefix_cache: bool | None = None,
                 quarantine_after: int | None = None,
                 rebuilds: int | None = None,
                 watchdog_s: float | None = None,
                 spec_k: int | None = None, spec_mode: str | None = None,
                 draft_model=None, spec_ngram: int | None = None,
                 spec_shed_occupancy: float | None = None,
                 mesh_tp: int | None = None, ledger=None,
                 kv_store=None, role: str | None = None,
                 device_pt: bool | None = None,
                 async_depth: int | None = None,
                 sched=None):
        if slots is None:
            slots = int(flag("gen_slots"))
        if slots <= 0:
            raise ValueError(
                "generation serving is disabled (FLAGS_gen_slots=0); set "
                "the flag or pass slots= explicitly")
        self.slots = int(slots)
        self.max_len = int(flag("gen_max_len") if max_len is None
                           else max_len)
        cfg_max = getattr(getattr(model, "config", None), "max_seq_len",
                          None)
        if cfg_max is not None:
            self.max_len = min(self.max_len, int(cfg_max))
        self._queue_max = int(flag("gen_queue_max") if queue_max is None
                              else queue_max)
        self._ttl_s = float(flag("gen_poll_ttl_s") if ttl_s is None
                            else ttl_s)
        self._eos_default = eos_token_id
        self._pad = int(pad_token_id)
        self._min_bucket = max(int(min_bucket), 1)
        # pacing knob: minimum gap between fused decode steps (throttle
        # a host-loop-bound engine, or make scheduling windows
        # deterministic in tests/chaos checks); 0 = run flat out
        self.step_wait_s = float(step_wait_s)
        self._model = model
        self._cache_dtype = cache_dtype
        self._paged = bool(flag("gen_paged") if paged is None else paged)
        # decode hot-loop knobs (hard-off by default; flags read HERE
        # only, never per token): a device-resident page table (paged
        # engines only — inert otherwise) and the async dispatch
        # lookahead depth (0 = the fully synchronous loop)
        self._device_pt = self._paged and bool(
            flag("gen_device_pt") if device_pt is None else device_pt)
        self._async_depth = max(0, int(flag("gen_async_depth")
                                       if async_depth is None
                                       else async_depth))
        self._prefill_chunk = int(flag("gen_prefill_chunk")
                                  if prefill_chunk is None
                                  else prefill_chunk)
        # self-healing knobs (all hard-off by default; see module doc)
        self._quarantine_after = int(flag("gen_quarantine_after")
                                     if quarantine_after is None
                                     else quarantine_after)
        self._rebuild_max = int(flag("gen_engine_rebuilds")
                                if rebuilds is None else rebuilds)
        self._watchdog_s = float(flag("gen_watchdog_s")
                                 if watchdog_s is None else watchdog_s)
        # speculative decoding (hard-off by default: gen_spec_k=0 keeps
        # the compiled surface and decode path byte-identical to the
        # pre-speculation build — flags are read HERE only, never on
        # the data path)
        self._spec_k = int(flag("gen_spec_k") if spec_k is None
                           else spec_k)
        self._spec_mode = str(flag("gen_spec_mode") if spec_mode is None
                              else spec_mode)
        self._spec_ngram = int(flag("gen_spec_ngram") if spec_ngram is None
                               else spec_ngram)
        self._spec_shed = float(flag("gen_spec_shed_occupancy")
                                if spec_shed_occupancy is None
                                else spec_shed_occupancy)
        self._draft_model = draft_model
        if self._spec_k > 0:
            if self._spec_mode not in ("ngram", "draft"):
                raise ValueError(
                    f"unknown gen_spec_mode {self._spec_mode!r}; expected "
                    "'ngram' or 'draft'")
            if self._spec_mode == "draft" and draft_model is None:
                raise ValueError(
                    "gen_spec_mode=draft needs a draft_model= (any model "
                    "with the init_cache/forward_with_cache contract)")
        else:
            self._spec_mode = "off"
        # tensor-parallel device layout (hard-off by default:
        # gen_mesh_tp=0 builds no mesh — DeviceLayout is the identity,
        # every compiled entry point is the plain single-device jit,
        # byte-identical to the pre-sharding build. The flag is read
        # HERE only, never on the decode hot path). Sharded params are
        # committed before any cache/entry-point construction so the
        # partitioner sees one consistent layout.
        from paddle_tpu.serving.layout import DeviceLayout
        self._layout = DeviceLayout(int(flag("gen_mesh_tp")
                                        if mesh_tp is None else mesh_tp))
        if self._layout.sharded:
            self._model = model = self._layout.shard_model(model)
            if self._draft_model is not None:
                self._draft_model = self._layout.shard_model(
                    self._draft_model)
        # per-bucket compiled draft-model proposers (mode=draft only)
        self._draft_fns: dict[int, Any] = {}
        # tokens_per_step books: decode-step emitted tokens over decode
        # iterations — distinguishes speculation wins (>1 per slot-step)
        # from batching wins; spec acceptance totals ride along
        self._emit_total = 0
        self._decode_iters = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_verify_steps = 0
        # XLA compile books: (entry point, shape signature) pairs seen.
        # The first call with a new signature IS the compile (jit caches
        # thereafter), so its wall clock approximates compile time; a
        # second-or-later signature on one entry point is a RECOMPILE —
        # the classic silent TPU perf killer this surfaces in health
        self._compiled_seen: set[tuple[str, Any]] = set()
        self._recompiles = 0
        self._recompile_ts: deque[float] = deque(maxlen=256)
        # performance-attribution books (hard-off by default:
        # gen_ledger=False builds neither, and every hot-path gate is a
        # single is-None attribute check — the FLAGS_trace pattern.
        # Flags are read HERE only, never per token). ledger= accepts
        # True/False to force, or a RequestLedger to share one.
        led = flag("gen_ledger") if ledger is None else ledger
        if led:
            from paddle_tpu.serving.ledger import GoodputMeter, RequestLedger
            self._ledger = (led if isinstance(led, RequestLedger)
                            else RequestLedger(int(flag(
                                "gen_ledger_records"))))
            self._goodput = GoodputMeter()
        else:
            self._ledger = None
            self._goodput = None
        # disaggregated-serving KV store (hard-off by default:
        # gen_kv_store=False builds no store and no role machinery —
        # every hot-path gate below is a single is-None check on
        # self._kv, same discipline as the ledger. Flags are read HERE
        # only). kv_store= accepts True/False to force, or a KVStore to
        # share one (how the in-proc tests model a fleet). The store
        # lives OUTSIDE _rebuild's pool/prefix replacement: serialized
        # host bytes survive engine self-healing by design.
        self._role = str(flag("gen_role") if role is None else role)
        if self._role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown gen_role {self._role!r}; expected "
                             "'prefill', 'decode' or 'both'")
        kv = flag("gen_kv_store") if kv_store is None else kv_store
        if kv:
            if not self._paged:
                raise ValueError("gen_kv_store requires the paged engine "
                                 "(gen_paged / paged=True): only paged "
                                 "KV is a transferable unit")
            from paddle_tpu.serving.kvstore import KVStore
            self._kv_owned = not isinstance(kv, KVStore)
            peers = tuple(p.strip() for p in
                          str(flag("gen_kv_peers")).split(",") if p.strip())
            self._kv = kv if isinstance(kv, KVStore) else KVStore(
                pages=int(flag("gen_kv_store_pages")),
                spill=str(flag("gen_kv_spill_dir")) or None,
                fetch_timeout_s=float(flag("gen_kv_fetch_timeout_s")),
                hedge_ms=float(flag("gen_kv_hedge_ms")),
                breaker=int(flag("gen_kv_breaker")),
                breaker_backoff_s=float(flag("gen_kv_breaker_backoff_s")),
                peers=peers)
            # admission-level fetch budget across one gen's page chain
            self._kv_admit_s = float(flag("gen_kv_admit_timeout_s"))
            # prefill-tier replicas are producers: they publish but
            # never fetch; decode-tier (and 'both') replicas fetch at
            # admission. Whoever ran a prefill publishes its pages —
            # that write is what makes the store fleet-wide.
            self._kv_fetch = self._role in ("decode", "both")
            self._kv_published = 0       # pages this engine put
            self._kv_fetched_pages = 0   # pages admitted from the store
            self._kv_fetched_bytes = 0
            self._kv_demoted = 0         # prefix evictions demoted, not
            self._kv_recomputed = 0      # dropped; resumed-prefill debt
            self._kv_degraded = 0        # fetches degraded to recompute
        else:
            self._kv = None
            self._kv_owned = False
            self._kv_fetch = False
            self._kv_admit_s = 0.0
        # SLO-aware tenant-fair scheduler (hard-off by default:
        # gen_sched=False builds none, and every hot-path gate below is
        # a single is-None attribute check — the ledger discipline.
        # Flags are read HERE only, never per iteration). sched=
        # accepts True/False to force, or a GenScheduler to share one —
        # how the serving layer routes FrameService/DynamicBatcher shed
        # decisions through the same policy object as the loop.
        sc = flag("gen_sched") if sched is None else sched
        if sc:
            from paddle_tpu.serving.scheduler import GenScheduler
            self._sched = (sc if isinstance(sc, GenScheduler)
                           else GenScheduler())
            if self._ledger is not None:
                self._sched.attach_book(self._ledger.book)
        else:
            self._sched = None
        # the scheduler's decision for the CURRENT loop iteration
        # (None whenever gen_sched is off — hot paths gate on it)
        self._plan = None

        if self._paged:
            P = int(flag("gen_page_tokens") if page_tokens is None
                    else page_tokens)
            if P < 1:
                raise ValueError(f"page_tokens must be >= 1, got {P}")
            self._page_tokens = P
            self._maxp = -(-self.max_len // P)       # pages per table
            npages = int(flag("gen_pages") if pages is None else pages)
            if npages <= 0:
                # equal HBM to the contiguous layout by default
                npages = self.slots * self._maxp
            self._pool = _PagePool(npages)
            self._prefix = (_PrefixCache(P)
                            if (flag("gen_prefix_cache")
                                if prefix_cache is None else prefix_cache)
                            else None)
            # host-side page tables, uploaded per compiled call (0 =
            # null page); rows zero whenever the slot is free
            self._pt = np.zeros((self.slots, self._maxp), np.int32)
            stat_set("gen/pages_free", self._pool.free_count)
        else:
            self._pool = None
            self._prefix = None
            self._pt = None
        # gen_device_pt: device-resident mirror of the host table,
        # updated with dirty-row .at[slot].set writes on admit/retire
        # (the host array stays the scheduler's source of truth).
        # Default path instead caches ONE whole-table upload per
        # schedule change (_sched_pt) so an unchanged table is not
        # re-shipped every iteration — prefill chunks, plain steps and
        # the spec step's second upload all share it.
        self._pt_dev = (self._layout.place_pt(self._pt)
                        if self._device_pt else None)
        self._sched_pt = None
        self._state: dict[str, Any] = self._init_state()
        # topology for stats()/health: static for the engine's lifetime
        # (the cache pool never resizes), so computed once here
        import jax
        kv_bytes = sum(int(x.nbytes) for x in
                       jax.tree_util.tree_leaves(self._state["cache"]))
        self._device_info = self._layout.describe(kv_bytes)
        if self._paged:
            self._step = self._build_paged_step()
            self._prefill_fn = self._build_paged_prefill()
            self._spec_step = (self._build_paged_spec_step()
                               if self._spec_k > 0 else None)
        else:
            self._step = self._build_step()
            self._prefill_fn = self._build_prefill()
            self._spec_step = (self._build_spec_step()
                               if self._spec_k > 0 else None)

        self._cond = threading.Condition()
        self._queue: deque[Generation] = deque()
        # gen_async_depth lookahead books: dispatched decode steps whose
        # token readback is deferred — entries are (stepped snapshot,
        # device tokens, epoch at dispatch, chip share); oldest first
        self._pending: deque[tuple] = deque()
        self._slot_gen: list[Generation | None] = [None] * self.slots
        self._gens: dict[str, Generation] = {}
        self._stopping = False
        self._broken: str | None = None
        # self-healing books: crash fingerprints, quarantine set, reaped
        # tombstones (typed GenerationExpired instead of unknown-id),
        # rebuild/trap counters, watchdog heartbeat + stuck latch, and
        # the state epoch that invalidates an in-flight compiled call's
        # results after the watchdog failed its generations
        self._crash_counts: dict[str, int] = {}
        # co-tenant-ambiguous (fused decode / watchdog) trap books:
        # "suspect" fingerprints need 2 independent hits before
        # quarantine so a neighbor's poison can't evict bystanders
        self._suspect_counts: dict[str, int] = {}
        self._quarantined: dict[str, str] = {}
        self._expired: dict[str, float] = {}
        self._rebuilds = 0
        self._consec_traps = 0
        self._epoch = 0
        self._stuck = False
        # generation currently blocked in _kv_admit_fetch (lock held by
        # no one while the store I/O runs): the watchdog counts it as
        # busy work and fails it resumable when the beat goes stale
        self._admitting: Generation | None = None
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gen-engine")
        self._thread.start()
        self._watch_stop = threading.Event()
        self._watchdog: threading.Thread | None = None
        if self._watchdog_s > 0:
            self._watchdog = threading.Thread(target=self._watchdog_loop,
                                              daemon=True,
                                              name="gen-watchdog")
            self._watchdog.start()

    def _init_state(self) -> dict[str, Any]:
        """Fresh device-side engine state (the batched KV cache/page
        pool plus per-slot token/position/key/sampling arrays). Called
        at construction AND by :meth:`_rebuild` — self-healing replaces
        the whole device state, never patches a possibly-poisoned one."""
        import jax
        import jax.numpy as jnp

        proto = self._model.init_cache(1, self.max_len,
                                       dtype=self._cache_dtype)
        if self._paged:
            from paddle_tpu.models.generation import init_paged_cache
            cache = init_paged_cache(proto, self._pool.num_pages,
                                     self._page_tokens)
        else:
            cache = jax.tree_util.tree_map(
                lambda x: jnp.zeros((self.slots,) + x.shape, x.dtype),
                proto)
        state = {
            "cache": cache,
            "tok": jnp.zeros((self.slots,), jnp.int32),
            "pos": jnp.zeros((self.slots,), jnp.int32),
            "keys": jnp.zeros((self.slots, 2), jnp.uint32),
            "temp": jnp.zeros((self.slots,), jnp.float32),
            "top_k": jnp.zeros((self.slots,), jnp.int32),
            "top_p": jnp.ones((self.slots,), jnp.float32),
        }
        # commit to the device layout (identity at gen_mesh_tp=0): KV
        # leaves land sharded on the KV-head axis, scalars replicated,
        # matching the explicit shardings every entry point compiles with
        return self._layout.place_state(state, paged=self._paged)

    # -- compiled pieces ---------------------------------------------------
    def _build_step(self):
        """ONE fused decode for all slots: vmap the model's single-token
        cached forward over the slot axis with per-slot positions/keys/
        sampling params. Inactive slots compute too (fixed cost, fixed
        shapes) but their token/position state is frozen by the mask and
        their cache garbage is overwritten at the next admit."""
        import jax
        import jax.numpy as jnp

        model = self._model

        def one(cache, tok, idx, key, temp, top_k, top_p):
            logits, cache = model.forward_with_cache(
                tok[None, None], cache, index=idx)
            key, sub = jax.random.split(key)
            nxt = _sample_slot(logits[0, -1], sub, temp, top_k, top_p)
            return cache, nxt, key

        def step(state, active):
            cache, nxt, keys = jax.vmap(one)(
                state["cache"], state["tok"], state["pos"], state["keys"],
                state["temp"], state["top_k"], state["top_p"])
            tok = jnp.where(active, nxt, state["tok"])
            pos = state["pos"] + active.astype(jnp.int32)
            return dict(state, cache=cache, tok=tok, pos=pos,
                        keys=keys), tok

        return self._layout.jit_entry(step, self._state,
                                      paged=False, n_in=1, n_out=1)

    def _build_prefill(self):
        """Prefill one slot from a right-padded prompt bucket (compiled
        once per bucket length; ``slot``/``true_len`` are traced). The
        whole slot cache is overwritten, so stale state from the previous
        occupant never leaks into the new generation."""
        import jax
        import jax.numpy as jnp

        model, S, cache_dtype = self._model, self.max_len, self._cache_dtype

        def prefill(state, slot, padded, true_len, key, temp, top_k, top_p):
            b1 = model.init_cache(1, S, dtype=cache_dtype)
            logits, b1 = model.forward_with_cache(padded[None], b1,
                                                  index=0)
            key, sub = jax.random.split(key)
            tok0 = _sample_slot(logits[0, true_len - 1], sub, temp, top_k,
                                top_p)
            cache = jax.tree_util.tree_map(
                lambda big, sm: big.at[slot].set(sm), state["cache"], b1)
            return dict(
                cache=cache,
                tok=state["tok"].at[slot].set(tok0),
                pos=state["pos"].at[slot].set(true_len),
                keys=state["keys"].at[slot].set(key),
                temp=state["temp"].at[slot].set(temp),
                top_k=state["top_k"].at[slot].set(jnp.asarray(top_k,
                                                              jnp.int32)),
                top_p=state["top_p"].at[slot].set(top_p),
            ), tok0

        return self._layout.jit_entry(prefill, self._state,
                                      paged=False, n_in=7, n_out=1)

    def _build_paged_step(self):
        """ONE fused decode for all slots in paged mode: each slot
        gathers its page table into a contiguous cache view, runs the
        same single-token cached forward as the contiguous step, and
        the freshly written position is scattered back into its page
        outside the vmap (inactive/masked slots scatter to the null
        page). The gathered view is a step-local temporary — the
        persistent HBM is the page pool."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models.generation import paged_gather

        model, P, maxp = self._model, self._page_tokens, self._maxp
        slots = self.slots

        def one(pt_row, tok, idx, key, temp, top_k, top_p, pool):
            cache = paged_gather(pool, pt_row)
            logits, cache = model.forward_with_cache(
                tok[None, None], cache, index=idx)
            new = tuple(
                jax.lax.dynamic_slice_in_dim(c, idx, 1, axis=3)[:, 0, :, 0]
                for c in cache)                       # [L, Hkv, *rest]
            key, sub = jax.random.split(key)
            nxt = _sample_slot(logits[0, -1], sub, temp, top_k, top_p)
            return nxt, key, new

        def step(state, pt, active):
            pool = state["cache"]
            nxt, keys, new = jax.vmap(
                one, in_axes=(0, 0, 0, 0, 0, 0, 0, None))(
                pt, state["tok"], state["pos"], state["keys"],
                state["temp"], state["top_k"], state["top_p"], pool)
            pidx = jnp.clip(state["pos"] // P, 0, maxp - 1)
            pages = jnp.where(active, pt[jnp.arange(slots), pidx], 0)
            offs = state["pos"] % P
            pool = tuple(
                buf.at[pages, :, :, offs].set(n.astype(buf.dtype))
                for buf, n in zip(pool, new))
            tok = jnp.where(active, nxt, state["tok"])
            pos = state["pos"] + active.astype(jnp.int32)
            return dict(state, cache=pool, tok=tok, pos=pos,
                        keys=keys), tok

        return self._layout.jit_entry(step, self._state,
                                      paged=True, n_in=2, n_out=1)

    def _build_paged_prefill(self):
        """Prefill ONE chunk of one slot's prompt (compiled per padded
        chunk length): gather the slot's pages, forward the chunk at its
        absolute index against the shared-prefix context already in
        those pages, scatter the written positions back (padding
        redirected to the null page), and record the slot state as if
        this were the final chunk — a later chunk simply overwrites it,
        so the last chunk's sample/key/position land without a traced
        branch."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models.generation import paged_gather, paged_scatter

        model, P = self._model, self._page_tokens

        def prefill(state, pt, slot, padded, index, true_len, key, temp,
                    top_k, top_p):
            pool = state["cache"]
            row = pt[slot]
            cache = paged_gather(pool, row)
            logits, cache = model.forward_with_cache(padded[None], cache,
                                                     index=index)
            chunk = tuple(
                jax.lax.dynamic_slice_in_dim(c, index, padded.shape[0],
                                             axis=3)
                for c in cache)
            pool = paged_scatter(pool, row, chunk, index, P,
                                 length=true_len)
            key, sub = jax.random.split(key)
            tok0 = _sample_slot(logits[0, true_len - 1], sub, temp, top_k,
                                top_p)
            return dict(
                cache=pool,
                tok=state["tok"].at[slot].set(tok0),
                pos=state["pos"].at[slot].set(index + true_len),
                keys=state["keys"].at[slot].set(key),
                temp=state["temp"].at[slot].set(temp),
                top_k=state["top_k"].at[slot].set(jnp.asarray(top_k,
                                                              jnp.int32)),
                top_p=state["top_p"].at[slot].set(top_p),
            ), tok0

        return self._layout.jit_entry(prefill, self._state,
                                      paged=True, n_in=9, n_out=1)

    def _spec_pick_accept(self, jax, jnp, logits, key, temp, top_k, top_p,
                          draft, dlen):
        """Shared verify core of both spec steps (traced, per slot):
        compute the target's pick at every one of the K+1 forwarded
        positions — position ``i``'s pick drawing from the subkey of the
        ``i+1``-th split past the slot key, the exact per-emitted-token
        schedule — then accept the longest draft prefix matching those
        picks. Returns ``(out [K+1], emit, new_key)`` where
        ``out[:emit]`` are the emitted tokens (accepted drafts + the
        target's pick at the first mismatch) and ``new_key`` is the slot
        key advanced by exactly ``emit`` splits, so a slot's key
        schedule is indistinguishable from ``emit`` plain steps."""
        K = self._spec_k
        keys, subs, cur = [], [], key
        for _ in range(K + 1):
            cur, sub = jax.random.split(cur)
            keys.append(cur)
            subs.append(sub)
        picks = jnp.stack([
            _sample_slot(logits[i], subs[i], temp, top_k, top_p)
            for i in range(K + 1)])                          # [K+1]
        good = (picks[:K] == draft) & (jnp.arange(K) < dlen)
        acc = jnp.sum(jnp.cumprod(good.astype(jnp.int32)))
        j = jnp.arange(K + 1)
        out = jnp.where(j < acc, jnp.concatenate([draft, draft[-1:]]),
                        picks)
        new_key = jnp.stack(keys)[acc]       # acc+1 = emit splits in
        return out, acc + 1, new_key

    def _build_spec_step(self):
        """ONE fused speculative verify for all slots (contiguous mode):
        each slot forwards ``[pending, draft_1..draft_K]`` at its
        position — the multi-token prefill machinery — and accepts the
        longest draft prefix matching the target's per-position picks.
        Mixed speculating/non-speculating slots coexist: draft length 0
        degrades to a plain single-token step for that slot (identical
        pick at position 0; causal masking makes the extra positions
        inert). Rollback is position-pointer arithmetic: rejected-draft
        KV sits at positions >= the new decode index, which attention
        masks and later writes overwrite; admission reserved ``spec_k``
        scratch positions so the fixed K+1 write window never clamps."""
        import jax
        import jax.numpy as jnp

        model, slots = self._model, self.slots

        def one(cache, tok, idx, key, temp, top_k, top_p, draft, dlen):
            ids = jnp.concatenate([tok[None], draft])[None]   # [1, K+1]
            logits, cache = model.forward_with_cache(ids, cache,
                                                     index=idx)
            out, emit, new_key = self._spec_pick_accept(
                jax, jnp, logits[0], key, temp, top_k, top_p, draft,
                dlen)
            return cache, out, emit, new_key

        def step(state, active, drafts, dlens):
            cache, out, emit, keys = jax.vmap(one)(
                state["cache"], state["tok"], state["pos"], state["keys"],
                state["temp"], state["top_k"], state["top_p"], drafts,
                dlens)
            emit = jnp.where(active, emit, 0)
            last = jnp.take_along_axis(
                out, jnp.maximum(emit - 1, 0)[:, None], axis=1)[:, 0]
            tok = jnp.where(active, last, state["tok"])
            pos = state["pos"] + emit
            return dict(state, cache=cache, tok=tok, pos=pos,
                        keys=keys), out, emit

        return self._layout.jit_entry(step, self._state,
                                      paged=False, n_in=3, n_out=2)

    def _build_paged_spec_step(self):
        """Speculative verify in paged mode: gather each slot's pages,
        forward the K+1-token window, then scatter ONLY the emitted
        positions back through the page table — the rejected tail is
        redirected to the null page (page-refcount-safe truncation:
        rejected drafts never land in a live page, so rollback cannot
        interact with prefix-shared pages or refcounts)."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models.generation import paged_gather

        model, P, maxp = self._model, self._page_tokens, self._maxp
        K = self._spec_k

        def one(pt_row, tok, idx, key, temp, top_k, top_p, draft, dlen,
                pool):
            cache = paged_gather(pool, pt_row)
            ids = jnp.concatenate([tok[None], draft])[None]
            logits, cache = model.forward_with_cache(ids, cache,
                                                     index=idx)
            chunk = tuple(
                jax.lax.dynamic_slice_in_dim(c, idx, K + 1, axis=3)[:, 0]
                for c in cache)               # [L, Hkv, K+1, *rest]
            out, emit, new_key = self._spec_pick_accept(
                jax, jnp, logits[0], key, temp, top_k, top_p, draft,
                dlen)
            return out, emit, new_key, chunk

        def step(state, pt, active, drafts, dlens):
            pool = state["cache"]
            out, emit, keys, chunks = jax.vmap(
                one, in_axes=(0,) * 9 + (None,))(
                pt, state["tok"], state["pos"], state["keys"],
                state["temp"], state["top_k"], state["top_p"], drafts,
                dlens, pool)
            emit = jnp.where(active, emit, 0)
            j = jnp.arange(K + 1)
            pos = state["pos"][:, None] + j[None, :]      # [slots, K+1]
            pidx = jnp.clip(pos // P, 0, maxp - 1)
            pages = jnp.take_along_axis(pt, pidx, axis=1)
            # truncation: positions past the accept point (and every
            # position of inactive slots, emit 0) go to the null page
            pages = jnp.where(j[None, :] < emit[:, None], pages, 0)
            offs = pos % P
            pool = tuple(
                buf.at[pages, :, :, offs].set(
                    jnp.moveaxis(ch, 3, 1).astype(buf.dtype))
                for buf, ch in zip(pool, chunks))
            last = jnp.take_along_axis(
                out, jnp.maximum(emit - 1, 0)[:, None], axis=1)[:, 0]
            tok = jnp.where(active, last, state["tok"])
            pos1 = state["pos"] + emit
            return dict(state, cache=pool, tok=tok, pos=pos1,
                        keys=keys), out, emit

        return self._layout.jit_entry(step, self._state,
                                      paged=True, n_in=4, n_out=2)

    # -- drafters (host side) ----------------------------------------------
    def _propose(self, ctx: np.ndarray, cap: int) -> np.ndarray:
        """Draft up to ``cap`` tokens for one slot from its own context
        (prompt + emitted tokens so far). May return fewer (or none —
        the slot then takes a plain step this iteration)."""
        if self._spec_mode == "draft":
            return self._draft_propose(ctx, cap)
        from paddle_tpu.models.generation import ngram_propose
        return ngram_propose(ctx, cap, max_ngram=self._spec_ngram)

    def _draft_propose(self, ctx: np.ndarray, cap: int) -> np.ndarray:
        import jax.numpy as jnp

        T = int(ctx.size)
        bucket = self._bucket(T)
        fn = self._draft_fns.get(bucket)
        if fn is None:
            fn = self._draft_fns[bucket] = self._build_draft_fn(bucket)
        padded = np.full((bucket,), self._pad, np.int32)
        padded[:T] = ctx
        t0 = time.perf_counter()
        out = np.asarray(fn(jnp.asarray(padded),
                            jnp.asarray(T, jnp.int32)))
        self._note_compile("draft", bucket, time.perf_counter() - t0)
        return out[:cap]

    def _build_draft_fn(self, bucket: int):
        """Compiled greedy K-token lookahead of the draft model over a
        right-padded context bucket (one compile per pow-2 bucket, the
        prefill discipline): prefill the context, then argmax-decode K
        tokens against the draft's own scratch cache. The decode tail is
        a ``lax.fori_loop`` — one traced body regardless of K, so draft
        compile time (the ``gen/compile_s`` histogram) no longer grows
        with ``spec_k`` the way the former K−1-times-unrolled graph did.
        The draft cache is call-local — the draft never holds persistent
        per-slot state, so engine rebuilds and slot churn cannot
        desynchronize it."""
        import jax
        import jax.numpy as jnp

        draft, K, dtype = self._draft_model, self._spec_k, self._cache_dtype

        def fn(padded, true_len):
            cache = draft.init_cache(1, bucket + K, dtype=dtype)
            logits, cache = draft.forward_with_cache(padded[None], cache,
                                                     index=0)
            tok0 = jnp.argmax(logits[0, true_len - 1]).astype(jnp.int32)
            idx = jnp.asarray(true_len, jnp.int32)

            def body(i, carry):
                out, cache = carry
                logits, cache = draft.forward_with_cache(
                    out[i - 1][None, None], cache, index=idx + i - 1)
                nxt = jnp.argmax(logits[0, -1]).astype(jnp.int32)
                return out.at[i].set(nxt), cache

            out0 = jnp.zeros((K,), jnp.int32).at[0].set(tok0)
            out, _ = jax.lax.fori_loop(1, K, body, (out0, cache))
            return out

        return self._layout.jit_aux(fn, n_in=2)

    def _bucket(self, n: int) -> int:
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_len)

    # -- stream-lifecycle tracing + compile observability -------------------
    def _gen_span(self, gen: Generation, name: str, **attrs):
        """Span for per-generation work: linked under the generation's
        stream trace id when it carries one (the cross-replica stream
        timeline obs_dump merges), a plain engine-local span otherwise.
        The shared no-op when tracing is off — the unflagged path pays
        one module-attribute read."""
        if _trace._ACTIVE is None:
            return _trace._NOOP
        if gen.trace_id is not None:
            return _trace.server_span(name, gen.trace_id, None,
                                      gen=gen.gen_id, **attrs)
        return _trace.span(name, **attrs)

    def _gen_event(self, gen: Generation, name: str, **attrs) -> None:
        """Zero-duration stream-lifecycle event (admitted / retire /
        decode sample) recorded under the stream trace id. No-op unless
        tracing is on AND the generation carries a stream id."""
        if _trace._ACTIVE is None or gen.trace_id is None:
            return
        with _trace.server_span(name, gen.trace_id, None,
                                gen=gen.gen_id, **attrs):
            pass

    def _note_compile(self, entry: str, sig, dt: float) -> bool:
        """Bookkeep one compiled-entry-point call: the first call with a
        new (entry, shape-signature) pair is the XLA compile (every
        later call hits the jit cache), so ``dt`` — that call's wall
        clock — lands in the ``gen/compile_s`` histogram. A second or
        later signature on one entry point counts as a recompile; their
        recent-window count is the recompile-storm gauge in
        :meth:`stats`. After the first sight this is one set lookup.

        Returns True when THIS call compiled (first sight of the pair):
        its wall clock was compile-dominated, which the goodput meter
        attributes to the ``recompile`` bucket instead of device work."""
        key = (entry, sig)
        if key in self._compiled_seen:
            return False
        with self._cond:
            if key in self._compiled_seen:
                return False
            first = not any(k[0] == entry for k in self._compiled_seen)
            self._compiled_seen.add(key)
            if not first:
                self._recompiles += 1
                self._recompile_ts.append(time.monotonic())
        observe("gen/compile_s", dt)
        stat_add("gen/compiles")
        if not first:
            stat_add("gen/recompiles")
        return True

    def _ledger_finalize(self, gen: Generation, outcome: str) -> None:
        """Finalize the generation's ledger record exactly once (caller
        holds the lock; every retire path calls this). The gated
        ``gen/ledger`` event makes the finalize visible in the stream
        trace, so obs_dump joins phase records to the same stream id a
        failover resume carries across replicas."""
        if self._ledger is None or gen.ledgered:
            return
        gen.ledgered = True
        rec = self._ledger.finalize(gen, outcome)
        self._gen_event(gen, "gen/ledger", outcome=outcome,
                        e2e_s=round(rec["e2e_s"], 6),
                        resumed=int(gen.rng_skip > 0))

    @property
    def sched(self):
        """The engine's :class:`~paddle_tpu.serving.scheduler.
        GenScheduler`, or None with ``FLAGS_gen_sched`` off — how the
        serving layer routes FrameService/batcher shed decisions
        through the same policy object."""
        return self._sched

    # -- public surface ----------------------------------------------------
    def start(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
              top_k: int = 0, top_p: float = 1.0, eos_token_id=_UNSET,
              seed: int = 0, rng_skip: int = 0,
              trace_id: str | None = None,
              tenant: str | None = None,
              fingerprint: str | None = None,
              priority: str | None = None) -> str:
        """Enqueue a generation; returns its id immediately. Raises
        :class:`EngineOverloaded` (retryable) when every slot is busy and
        the admit queue is at ``queue_max``, and the typed
        :class:`RequestQuarantined` when the request's crash fingerprint
        is quarantined. ``rng_skip`` advances the per-(prompt, seed)
        sampling-key schedule by that many splits before the first
        token — how a resumed sampled stream replays its RNG position
        (see ``models.generation.advance_key``); greedy requests ignore
        it. ``trace_id`` is the caller's stream trace id (wire header
        ``st``): when tracing is on, the engine records this
        generation's slot-lifecycle events under it. ``tenant`` (wire
        header ``tn``) is the caller's attribution identity — the
        ledger books this generation's tokens/chip-seconds/queue-wait
        under it when ``FLAGS_gen_ledger`` is on. ``fingerprint``
        (wire header ``fp``) overrides the crash fingerprint computed
        from the request itself: a resumed stream's replay prompt grew
        by the delivered tokens, so the resuming client passes the
        ORIGINAL stream's fingerprint — quarantine then recognizes
        resumed poison instead of admitting it under a fresh hash.
        ``priority`` (wire header ``pc``) is the request's scheduling
        class (interactive / batch / best_effort) — consulted only when
        ``FLAGS_gen_sched`` built a scheduler; ignored (recorded but
        inert) otherwise."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        rng_skip = int(rng_skip)
        if rng_skip < 0:
            raise ValueError("rng_skip must be >= 0")
        # with speculation on, a slot's verify step writes a fixed
        # K+1-token window at the decode position — the last emitted
        # token can sit at prompt+max_new-1, so spec_k scratch positions
        # past the declared worst case keep that write in bounds
        # (dynamic_update_slice clamps its start; an out-of-bounds
        # window would silently shift live positions)
        reserve = prompt.size + max_new_tokens + self._spec_k
        if reserve > self.max_len:
            spec = (f" + spec_k ({self._spec_k}) scratch"
                    if self._spec_k else "")
            fix = (" or lower FLAGS_gen_spec_k" if self._spec_k else "")
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}){spec} exceeds the engine's per-slot "
                f"capacity ({self.max_len}); raise FLAGS_gen_max_len"
                + fix)
        if self._paged:
            need = -(-reserve // self._page_tokens)
            if need > self._pool.num_pages:
                raise ValueError(
                    f"request needs {need} pages but the pool only has "
                    f"{self._pool.num_pages}; raise FLAGS_gen_pages")
        eos = self._eos_default if eos_token_id is _UNSET else eos_token_id
        gen = Generation(uuid.uuid4().hex[:16], prompt, max_new_tokens,
                         float(temperature), int(top_k), float(top_p),
                         None if eos is None else int(eos), int(seed))
        gen.rng_skip = rng_skip
        if fingerprint:
            gen.fingerprint = str(fingerprint)
        if trace_id:
            gen.trace_id = str(trace_id)
        if tenant:
            gen.tenant = str(tenant)
        if self._sched is not None:
            gen.pclass = self._sched.classify(priority)
        with self._cond:
            if self._stopping:
                raise RuntimeError("GenerationEngine is stopped")
            if self._broken is not None:
                raise RuntimeError(
                    f"GenerationEngine is broken: {self._broken}")
            if (self._quarantine_after > 0
                    and gen.fingerprint in self._quarantined):
                stat_add("gen/quarantine_rejected")
                raise RequestQuarantined(
                    f"{QUARANTINE_MARKER} request {gen.fingerprint} "
                    f"trapped the engine "
                    f"{self._crash_counts.get(gen.fingerprint, 0)} "
                    f"time(s) (last: "
                    f"{self._quarantined[gen.fingerprint]}); refusing "
                    "to re-admit it", fingerprint=gen.fingerprint)
            if self._stuck:
                # the decode loop is wedged in a device call; shed
                # retryably so the routed layer sends work elsewhere
                stat_add("gen/shed")
                raise EngineOverloaded(
                    "engine stuck: decode loop unresponsive "
                    f"(gen_watchdog_s={self._watchdog_s:g}); retry "
                    "elsewhere", retry_after_s=_jittered(0.5))
            free = sum(g is None for g in self._slot_gen)
            pending = len(self._queue) - free
            shed = (self._sched.shed_start(gen.pclass, pending,
                                           self._queue_max)
                    if self._sched is not None
                    else (self._queue_max > 0
                          and pending >= self._queue_max))
            if shed:
                stat_add("gen/shed")
                pool = ("" if not self._paged else
                        f", {self._pool.free_count}/"
                        f"{self._pool.num_pages} pages free")
                raise EngineOverloaded(
                    f"engine full: {self.slots} slots busy, "
                    f"{len(self._queue)} queued (queue_max="
                    f"{self._queue_max}){pool}",
                    retry_after_s=_jittered(0.25))
            if self._sched is not None:
                self._sched.on_enqueue(gen)
            self._queue.append(gen)
            self._gens[gen.gen_id] = gen
            stat_set("gen/queue_depth", len(self._queue))
            self._cond.notify_all()
        return gen.gen_id

    def poll(self, gen_id: str, start: int = 0,
             wait_s: float = 0.0) -> dict:
        """Drain tokens past ``start``; blocks up to ``wait_s`` for new
        ones (long-poll). Returns ``{"tokens", "done", "error",
        "queued"}``. Polling refreshes the generation's TTL — a client
        that stops polling for ``ttl_s`` is treated as disconnected and
        its slot reclaimed."""
        start = max(int(start), 0)
        deadline = time.monotonic() + max(float(wait_s), 0.0)
        with self._cond:
            gen = self._gens.get(gen_id)
            if gen is None:
                if gen_id in self._expired:
                    # reaped by the TTL (possibly while this poll was
                    # in flight): typed, so the caller can tell "your
                    # stream expired HERE" from "never started here"
                    stat_add("gen/expired_polls")
                    raise GenerationExpired(
                        f"{EXPIRED_MARKER} generation {gen_id} was "
                        "reaped by the poll TTL (client presumed "
                        "disconnected); restart it")
                raise KeyError(f"unknown generation {gen_id!r} "
                               "(finished long ago, evicted, or never "
                               "started here)")
            gen.last_poll = time.monotonic()
            while (not gen.done and len(gen.tokens) <= start
                   and not self._stopping):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                gen.last_poll = time.monotonic()
            if gen.done:
                # this response tells the caller the generation finished
                # and hands over every token past ``start`` — fully
                # delivered (the condition a sticky drain waits on
                # before a replica may stop)
                gen.delivered = True
                self._ledger_finalize(
                    gen, "complete" if gen.error is None else "failed")
            return {"tokens": list(gen.tokens[start:]), "done": gen.done,
                    "error": gen.error,
                    "queued": gen.slot is None and not gen.done}

    def cancel(self, gen_id: str) -> bool:
        """Cancel a generation and free its slot (idempotent; unknown
        ids return False). A freed slot is eligible for the very next
        admit."""
        with self._cond:
            gen = self._gens.pop(gen_id, None)
            if gen is None:
                return False
            gen.cancelled = True
            if not gen.done:
                gen.done = True
                gen.error = gen.error or "cancelled"
                self._release_slot_locked(gen, evicted=True)
                try:
                    self._queue.remove(gen)
                except ValueError:
                    pass
                stat_set("gen/queue_depth", len(self._queue))
                self._gen_event(gen, "gen/retire", reason="cancelled",
                                tokens=len(gen.tokens))
            # covers the done-but-undelivered case too: a cancel is the
            # last event this engine will ever see for the generation
            self._ledger_finalize(gen, "cancelled")
            self._cond.notify_all()
        return True

    def stats(self) -> dict:
        """Slot + page-pool occupancy snapshot (shipped in the serving
        ``health`` op — routers/probes see generation capacity AND, in
        paged mode, how much of the page pool and prefix cache is
        live)."""
        with self._cond:
            active = sum(g is not None for g in self._slot_gen)
            doc = {"slots": self.slots, "active": active,
                   "free": self.slots - active,
                   "queued": len(self._queue),
                   "generations": len(self._gens),
                   # running, queued, or finished-but-not-yet-polled-to-
                   # done: the work a sticky drain must wait out (done
                   # generations whose final poll already went out do
                   # NOT count — the client has everything)
                   "undelivered": sum(
                       1 for g in self._gens.values()
                       if not (g.done and g.delivered)),
                   "max_len": self.max_len,
                   "broken": self._broken,
                   "stuck": self._stuck,
                   "rebuilds": self._rebuilds,
                   "quarantined": len(self._quarantined),
                   # emitted tokens per fused decode iteration: >1.0
                   # means speculation is landing (batching wins show up
                   # in aggregate tokens/s, not here — this isolates the
                   # per-stream speedup the controller cares about)
                   "tokens_per_step": (
                       self._emit_total / self._decode_iters
                       if self._decode_iters else 0.0),
                   # XLA compile observability: total distinct compiled
                   # (entry, shape) signatures, how many were re-compiles
                   # of an already-compiled entry point, and the storm
                   # gauge (recompiles in the last 60s — sustained churn
                   # here means traffic shapes defeat the bucketing)
                   "compiles": len(self._compiled_seen),
                   "recompiles": self._recompiles,
                   "recompile_storm": sum(
                       1 for t in self._recompile_ts
                       if time.monotonic() - t < 60.0),
                   # device topology (static per engine): platform,
                   # device count, mesh axis sizes (None mesh =
                   # unsharded), total + per-device KV bytes — the
                   # placement inputs a control plane reads next to
                   # occupancy. A mesh-backed engine is ONE replica;
                   # this block is how its N devices stay visible.
                   "device": dict(self._device_info),
                   "paged": self._paged,
                   # decode hot-loop knobs (gen_device_pt /
                   # gen_async_depth) + current lookahead occupancy, so
                   # bench/chaos harnesses can assert which loop ran
                   "device_pt": self._device_pt,
                   "async_depth": self._async_depth,
                   "pending_steps": len(self._pending)}
            if self._spec_k > 0:
                prop = self._spec_proposed
                doc["spec"] = {
                    "k": self._spec_k,
                    "mode": self._spec_mode,
                    "proposed": prop,
                    "accepted": self._spec_accepted,
                    "rejected": prop - self._spec_accepted,
                    "accept_rate": (self._spec_accepted / prop
                                    if prop else 0.0),
                    "verify_steps": self._spec_verify_steps,
                    "shed_occupancy": self._spec_shed,
                }
            if self._paged:
                doc.update(
                    page_tokens=self._page_tokens,
                    pages=self._pool.num_pages,
                    pages_free=self._pool.free_count,
                    prefix_entries=(0 if self._prefix is None
                                    else len(self._prefix)))
            # performance attribution (FLAGS_gen_ledger only): the loop
            # goodput taxonomy and per-tenant books ride health's
            # generators block, so MetricsHub rolls them up fleet-wide
            # with no extra wire surface
            if self._goodput is not None:
                doc["goodput"] = self._goodput.snapshot()
            if self._ledger is not None:
                doc["tenants"] = self._ledger.tenants()
            # scheduler books (FLAGS_gen_sched only): preemption/shed/
            # quota counters + class weights. Absent with the scheduler
            # off so the default health doc is byte-identical.
            if self._sched is not None:
                doc["sched"] = self._sched.snapshot()
            # disaggregated serving (FLAGS_gen_kv_store only): store
            # tiers + this engine's produce/consume counters. Absent
            # with the store off so the default health doc is
            # byte-identical to the pre-store build.
            if self._kv is not None:
                doc["kv"] = dict(self._kv.snapshot(),
                                 role=self._role,
                                 published=self._kv_published,
                                 fetched_pages=self._kv_fetched_pages,
                                 fetched_bytes=self._kv_fetched_bytes,
                                 demoted=self._kv_demoted,
                                 prefill_recomputed=self._kv_recomputed,
                                 fetch_degraded=self._kv_degraded)
            return doc

    def ledger_dump(self, limit: int | None = None) -> dict | None:
        """Finalized per-request phase records + tenant book + goodput
        snapshot (the ``ledger_dump`` wire op's per-engine payload), or
        None while ``FLAGS_gen_ledger`` is off."""
        if self._ledger is None:
            return None
        doc = {"records": self._ledger.records(limit),
               "tenants": self._ledger.tenants()}
        if self._goodput is not None:
            doc["goodput"] = self._goodput.snapshot()
        return doc

    def clear_prefix_cache(self) -> int:
        """Drop every prefix-cache entry no live generation references
        (an operational memory-pressure valve; also how the tests assert
        the pool drains back to full). Returns pages freed."""
        with self._cond:
            if self._prefix is None:
                return 0
            freed = self._prefix.evict(self._pool.num_pages, self._pool,
                                       demote=(self._kv_demote
                                               if self._kv is not None
                                               else None))
            stat_set("gen/pages_free", self._pool.free_count)
            return freed

    def canary(self, timeout_s: float = 5.0, prompt_token: int = 1) -> dict:
        """One-token liveness decode through the real admit → prefill →
        sample path: *engine* liveness as distinct from *wire* liveness
        ("device healthy" vs "port open") — what the serving ``health``
        op ships per generator under ``deep=True``. A full engine counts
        as alive (``busy=True``: it is making progress for someone);
        broken/stuck/timed-out engines report ``ok=False`` with the
        error. Returns ``{"ok", "busy", "latency_s", "error"}``."""
        t0 = time.monotonic()
        try:
            gid = self.start(np.asarray([int(prompt_token)], np.int32), 1)
        except EngineOverloaded:
            return {"ok": True, "busy": True,
                    "latency_s": time.monotonic() - t0, "error": None}
        except RuntimeError as e:        # broken / quarantined canary
            return {"ok": False, "busy": False,
                    "latency_s": time.monotonic() - t0,
                    "error": f"{type(e).__name__}: {e}"}
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        ok, err = False, f"canary timed out after {timeout_s:g}s"
        try:
            while time.monotonic() < deadline:
                doc = self.poll(gid, wait_s=min(0.25, float(timeout_s)))
                if doc["done"]:
                    ok = doc["error"] is None
                    err = doc["error"]
                    break
        except (KeyError, RuntimeError) as e:
            err = f"{type(e).__name__}: {e}"
        finally:
            self.cancel(gid)
        return {"ok": ok, "busy": False,
                "latency_s": time.monotonic() - t0, "error": err}

    def close(self) -> None:
        """Stop the loop; error out queued/active generations."""
        with self._cond:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify_all()
        self._watch_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
        self._thread.join(timeout=10.0)
        with self._cond:
            for gen in list(self._gens.values()):
                if not gen.done:
                    gen.done = True
                    gen.error = gen.error or "engine stopped"
                    gen.slot = None
                    self._gen_event(gen, "gen/retire", reason="stopped",
                                    tokens=len(gen.tokens))
                self._ledger_finalize(gen, "stopped")
                gen.pages = []
            self._slot_gen = [None] * self.slots
            self._queue.clear()
            self._pending.clear()
            if self._paged:
                self._pt[:] = 0
                self._pt_sync_full_locked()
            self._cond.notify_all()
        if self._kv is not None and self._kv_owned:
            self._kv.close()   # shared stores outlive their engines

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- scheduler loop ----------------------------------------------------
    def _loop(self) -> None:
        import jax.numpy as jnp

        while True:
            with self._cond:
                if self._stopping:
                    return
                self._last_beat = time.monotonic()   # watchdog heartbeat
                if (not self._queue
                        and not any(g is not None for g in self._slot_gen)):
                    # idle: wake on new work, and periodically anyway so
                    # TTL reaping runs while nothing is streaming
                    t_idle = (time.perf_counter()
                              if self._goodput is not None else 0.0)
                    self._cond.wait(timeout=0.25)
                    if self._goodput is not None:
                        self._goodput.note("admission_idle",
                                           time.perf_counter() - t_idle)
                    if self._stopping:
                        return
            try:
                if self._stuck:
                    # the watchdog failed this loop's generations while
                    # a call was (apparently) wedged; whatever state the
                    # call left behind is garbage — rebuild or break
                    raise _EpochChanged("watchdog marked the engine "
                                        "stuck")
                self._reap_expired()
                if self._sched is not None:
                    # one brain, once per iteration: re-order the wait
                    # queue (class rank + fair tags) and fix this
                    # iteration's budgets; park victims when an
                    # interactive head is waiting on a full engine
                    with self._cond:
                        self._plan = self._sched.plan(self._queue,
                                                      self._slot_gen)
                    if self._plan.preempt:
                        self._preempt_tick()
                if self._paged:
                    progressed = self._admit_paged()
                    progressed |= self._prefill_tick()
                    progressed |= self._decode_step(jnp)
                    if not progressed:
                        # queue blocked on pages and nothing to step:
                        # wait for a cancel/TTL/poll to free capacity
                        # instead of spinning
                        t_idle = (time.perf_counter()
                                  if self._goodput is not None else 0.0)
                        with self._cond:
                            if not self._stopping:
                                self._cond.wait(timeout=0.05)
                        if self._goodput is not None:
                            self._goodput.note(
                                "admission_idle",
                                time.perf_counter() - t_idle)
                else:
                    self._admit()
                    self._decode_step(jnp)
                if self._goodput is not None:
                    # close this iteration's taxonomy: the un-noted
                    # remainder is host-side gather/bookkeeping (or the
                    # stuck latch, while the watchdog has it marked)
                    self._goodput.tick("watchdog_stuck" if self._stuck
                                       else "host_gather")
            except Exception as e:   # device-side failure: fail loudly
                with self._cond:
                    self._consec_traps += 1
                    consec = self._consec_traps
                if self._rebuild_max > 0 and consec <= self._rebuild_max:
                    try:              # self-heal: fail active gens,
                        self._rebuild(e)   # fresh state, re-admit
                        continue
                    except Exception as e2:   # rebuild itself trapped
                        self._break(e2)
                        return
                self._break(e)       # terminal: refuse new work,
                return               # keep pollers sane

    def _note_trap(self, gens: list[Generation], e: BaseException, *,
                   exact: bool = False) -> None:
        """Record a prefill/decode trap against the implicated
        generations' crash fingerprints; a fingerprint that reaches its
        quarantine threshold is quarantined — its future starts get the
        typed :class:`RequestQuarantined`. Prefill traps implicate
        exactly the prefilling request (``exact=True``: threshold is
        ``gen_quarantine_after`` as configured). Fused-decode and
        watchdog traps implicate every stepped generation — when more
        than one was stepped those fingerprints are co-tenant-
        AMBIGUOUS: booked separately as "suspect" and requiring at
        least 2 independent hits before quarantine, so a neighbor's
        poison request can't get a well-behaved bystander quarantined
        off one shared trap. A trap implicating exactly one generation
        is exact by pigeonhole regardless of the site."""
        stat_add("gen/traps")
        if self._quarantine_after <= 0 or not gens:
            return
        exact = exact or len(gens) == 1
        need = (self._quarantine_after if exact
                else max(2, self._quarantine_after))
        books = self._crash_counts if exact else self._suspect_counts
        msg = f"{type(e).__name__}: {e}"
        with self._cond:
            for gen in gens:
                fp = gen.fingerprint
                books[fp] = books.get(fp, 0) + 1
                if not exact:
                    stat_add("gen/suspect_traps")
                if books[fp] >= need and fp not in self._quarantined:
                    self._quarantined[fp] = msg
                    stat_add("gen/quarantined")
            while len(books) > 1024:            # bounded books
                books.pop(next(iter(books)))

    # -- page-table device residency (gen_device_pt) -----------------------
    def _pt_sync_row_locked(self, slot: int) -> None:
        """Host table row ``slot`` changed (admit/retire): mirror ONLY
        that row to the device-resident table and drop the default
        path's cached whole-table upload. Caller holds the lock. The
        functional ``.at`` update leaves any snapshot an in-flight
        dispatch captured untouched."""
        if self._pt_dev is not None:
            self._pt_dev = self._pt_dev.at[slot].set(self._pt[slot])
        self._sched_pt = None

    def _pt_sync_full_locked(self) -> None:
        """The whole host table changed (reset/rebuild/break): rebuild
        the device-resident table wholesale and drop the cached
        upload. Caller holds the lock."""
        if self._pt_dev is not None:
            self._pt_dev = self._layout.place_pt(self._pt)
        self._sched_pt = None

    def _pt_device_locked(self, jnp):
        """The page-table operand for a compiled call. gen_device_pt:
        the incrementally maintained device-resident table.
        Default path: ONE whole-table upload cached until admit/retire
        dirties it — the fix for re-shipping an unchanged table every
        iteration (prefill chunks and the spec path's second upload
        included). Caller holds the lock; the returned array is a
        snapshot (functional updates never mutate it in place)."""
        if self._pt_dev is not None:
            return self._pt_dev
        if self._sched_pt is None:
            self._sched_pt = jnp.asarray(self._pt)
        return self._sched_pt

    def _fail_active_locked(self, msg: str) -> list[Generation]:
        """Fail every slotted generation loudly (queued generations
        never touched the device — they stay queued and survive the
        reset). Caller holds the lock and is about to discard/rebuild
        the device state, so pages are NOT returned to the old pool.
        Returns the failed generations."""
        victims = [g for g in self._slot_gen if g is not None]
        for g in victims:
            if not g.done:
                g.done = True
                g.error = msg
                self._gen_event(g, "gen/retire", reason="failed",
                                tokens=len(g.tokens))
                self._ledger_finalize(g, "failed")
            g.slot = None
            g.prefilling = False
            g.pages = []
        self._slot_gen = [None] * self.slots
        if self._paged:
            self._pt[:] = 0
            self._pt_sync_full_locked()
        self._pending.clear()         # deferred readbacks die with the
        self._epoch += 1              # epoch: in-flight compiled results
        stat_set("gen/slots_active", 0)   # are garbage from here on
        return victims

    def _rebuild(self, e: Exception) -> None:
        """Self-heal after a decode-loop trap: fail the active
        generations with the resumable ``engine reset:`` marker, replace
        the device state (cache pool, page books, prefix cache) wholesale,
        and re-admit — queued work proceeds, new starts are accepted.
        Raises if rebuilding itself fails (the caller then breaks)."""
        msg = f"{RESET_MARKER} {type(e).__name__}: {e}"
        stat_add("gen/rebuilds")
        fresh = self._init_state()           # allocate outside the lock
        with self._cond:
            self._rebuilds += 1
            self._fail_active_locked(msg)
            if self._paged:
                self._pool = _PagePool(self._pool.num_pages)
                if self._prefix is not None:
                    self._prefix = _PrefixCache(self._page_tokens)
                stat_set("gen/pages_free", self._pool.free_count)
            self._state = fresh
            self._stuck = False
            self._cond.notify_all()

    def _watchdog_loop(self) -> None:
        """Stuck-step detection: active work but no loop heartbeat for
        ``gen_watchdog_s`` → fail the stranded generations loudly (their
        clients resume elsewhere), shed new starts, and let the loop
        rebuild/break when the wedged call finally returns."""
        period = max(min(self._watchdog_s / 4.0, 1.0), 0.05)
        while not self._watch_stop.wait(period):
            victims: list[Generation] = []
            with self._cond:
                if self._stopping:
                    return
                if self._stuck or self._broken is not None:
                    continue
                # an admission-time KV fetch counts as busy work: the
                # admitting generation holds no slot yet, but a wedged
                # store read stalls the whole loop exactly like a
                # wedged compiled call
                admitting = self._admitting
                busy = (any(g is not None for g in self._slot_gen)
                        or admitting is not None)
                stalled = time.monotonic() - self._last_beat
                if not busy or stalled <= self._watchdog_s:
                    continue
                stat_add("gen/stuck")
                victims = self._fail_active_locked(
                    f"{RESET_MARKER} stuck step: decode loop "
                    f"unresponsive for {stalled:.1f}s "
                    f"(gen_watchdog_s={self._watchdog_s:g})")
                if admitting is not None and not admitting.done:
                    # stranded mid-admission (PR 8 contract): fail it
                    # resumable too — it was never slotted, so
                    # _fail_active_locked can't see it
                    admitting.done = True
                    admitting.error = (
                        f"{RESET_MARKER} stuck step: admission kv "
                        f"fetch unresponsive for {stalled:.1f}s "
                        f"(gen_watchdog_s={self._watchdog_s:g})")
                    self._gen_event(admitting, "gen/retire",
                                    reason="failed",
                                    tokens=len(admitting.tokens))
                    self._ledger_finalize(admitting, "failed")
                    victims = victims + [admitting]
                self._stuck = True
                self._cond.notify_all()
            self._note_trap(victims,
                            TimeoutError("stuck decode step"))

    def _break(self, e: Exception) -> None:
        msg = f"{type(e).__name__}: {e}"
        with self._cond:
            self._broken = msg
            self._stuck = False       # broken supersedes stuck
            for gen in list(self._gens.values()):
                if not gen.done:
                    gen.done = True
                    gen.error = msg
                    gen.slot = None
                    self._gen_event(gen, "gen/retire", reason="broken",
                                    tokens=len(gen.tokens))
                self._ledger_finalize(gen, "broken")
                gen.pages = []
            self._slot_gen = [None] * self.slots
            self._queue.clear()
            if self._paged:           # nothing runs on a broken engine;
                self._pt[:] = 0       # reset the books for stats() sanity
                self._pt_sync_full_locked()
                self._pool = _PagePool(self._pool.num_pages)
                if self._prefix is not None:
                    self._prefix = _PrefixCache(self._page_tokens)
            self._pending.clear()
            self._cond.notify_all()

    def _release_slot_locked(self, gen: Generation,
                             evicted: bool = False) -> None:
        if gen.slot is not None and self._slot_gen[gen.slot] is gen:
            self._slot_gen[gen.slot] = None
            if self._paged:
                self._pt[gen.slot] = 0
                self._pt_sync_row_locked(gen.slot)
            if evicted:
                stat_add("gen/evictions")
        if self._paged and gen.pages:
            # drop this generation's references; pages the prefix cache
            # also holds stay allocated (shareable) until evicted
            for pid in gen.pages:
                self._pool.release(pid)
            gen.pages = []
            stat_set("gen/pages_free", self._pool.free_count)
        gen.slot = None
        gen.prefilling = False
        stat_set("gen/slots_active",
                 sum(g is not None for g in self._slot_gen))

    def _tombstone_locked(self, gen_id: str) -> None:
        """Remember a reaped generation id (bounded) so a late poll
        gets the typed :class:`GenerationExpired`, not unknown-id."""
        self._expired[gen_id] = time.monotonic()
        while len(self._expired) > 256:        # oldest first (dict order)
            self._expired.pop(next(iter(self._expired)))

    def _reap_expired(self) -> None:
        if self._ttl_s <= 0:
            return
        now = time.monotonic()
        with self._cond:
            expired = [g for g in self._gens.values()
                       if now - max(g.created, g.last_poll) > self._ttl_s]
        for gen in expired:
            with self._cond:
                g = self._gens.get(gen.gen_id)
                if g is None:
                    continue
                # re-check under the lock: a poll that arrived while
                # this reap was walking the candidates refreshed the
                # TTL — it must keep its generation, not observe a
                # half-reclaimed slot
                if (time.monotonic() - max(g.created, g.last_poll)
                        <= self._ttl_s):
                    continue
                self._gens.pop(g.gen_id, None)
                self._tombstone_locked(g.gen_id)
                if not g.done:
                    g.done = True
                    g.error = (f"{EXPIRED_MARKER} poll TTL exceeded "
                               "(client gone?)")
                    self._gen_event(g, "gen/retire", reason="expired",
                                    tokens=len(g.tokens))
                    self._release_slot_locked(g, evicted=True)
                    try:
                        self._queue.remove(g)
                    except ValueError:
                        pass
                # done-but-never-delivered generations retire here too:
                # the reap is the last event this engine sees for them
                self._ledger_finalize(g, "expired")
                self._cond.notify_all()

    def _admit(self) -> None:
        while True:
            with self._cond:
                free = [s for s, g in enumerate(self._slot_gen)
                        if g is None]
                if not free or not self._queue:
                    stat_set("gen/queue_depth", len(self._queue))
                    return
                gen = self._queue.popleft()
                if gen.done:          # cancelled while queued
                    continue
                slot = free[0]
                self._slot_gen[slot] = gen
                gen.slot = slot
                if self._ledger is not None:
                    gen.admitted_ts = time.monotonic()
                    self._ledger.book_admission(gen, gen.admitted_ts)
                if self._sched is not None:
                    self._sched.note_admitted(gen)
                stat_set("gen/slots_active",
                         sum(g is not None for g in self._slot_gen))
                self._gen_event(gen, "gen/admitted", slot=slot,
                                prompt_len=int(gen.prompt.size))
            self._prefill(gen, slot)

    def _admit_paged(self) -> bool:
        """Assign free slots + page reservations to queued prompts, in
        FIFO order. A generation reserves pages for its declared worst
        case (prompt + max_new_tokens) minus whatever whole-page prefix
        the radix cache already holds; when the pool cannot cover the
        queue head even after LRU-evicting unreferenced cached pages,
        admission stalls (head-of-line — predictable under pressure;
        pages return via retire/cancel/TTL). Prefill itself happens
        chunk-by-chunk in :meth:`_prefill_tick`."""
        progressed = False
        while True:
            with self._cond:
                free = [s for s, g in enumerate(self._slot_gen)
                        if g is None]
                if not free or not self._queue:
                    stat_set("gen/queue_depth", len(self._queue))
                    return progressed
                gen = self._queue[0]
                if gen.done:                # cancelled while queued
                    self._queue.popleft()
                    continue
                P = self._page_tokens
                # spec_k extra positions: the verify step's fixed-width
                # scatter may touch one page past the declared worst
                # case (rejected offsets are null-page-masked, but the
                # ACCEPTED prefix must land in owned pages)
                # a parked (preempted) generation folded its emitted
                # tokens into the prompt: max_new shrinks by the same
                # amount, so its reservation never grows past the
                # original worst case (folded is 0 for fresh requests)
                need = -(-(gen.prompt.size + gen.max_new_tokens
                           - gen.folded + self._spec_k) // P)
                matched: list[int] = []
                if self._prefix is not None:
                    matched = self._prefix.match(gen.prompt, self._pool)
                if (self._kv is not None and self._kv_fetch
                        and self._prefix is not None):
                    epoch0 = self._epoch
                    matched += self._kv_admit_fetch(gen, matched)
                    if self._epoch != epoch0 or self._stuck:
                        # the store fetch ran with the lock released
                        # and a rebuild/watchdog reset landed under it:
                        # matched pages belong to the replaced pool —
                        # do NOT release them into the fresh one
                        return progressed
                    if gen.done:        # cancelled while fetching
                        for pid in matched:
                            self._pool.release(pid)
                        stat_set("gen/pages_free", self._pool.free_count)
                        continue        # loop top pops the dead head
                    if gen.rng_skip:
                        # a resumed stream's original prompt is
                        # prompt[:-rng_skip] (replay appended the
                        # delivered tokens); whatever of it the cache +
                        # store did not cover is recomputed prefill —
                        # the debt KV-native failover exists to zero
                        debt = max(0, (int(gen.prompt.size)
                                       - int(gen.rng_skip))
                                   - len(matched) * P)
                        self._kv_recomputed += debt
                        if debt:
                            stat_add("gen/kv_prefill_recomputed", debt)
                short = (need - len(matched)) - self._pool.free_count
                if short > 0 and self._prefix is not None:
                    self._prefix.evict(short, self._pool,
                                       demote=(self._kv_demote
                                               if self._kv is not None
                                               else None))
                if need - len(matched) > self._pool.free_count:
                    for pid in matched:     # give the hits back; retry
                        self._pool.release(pid)   # when pages free up
                    if (self._plan is not None
                            and self._plan.hol_window > 0
                            and self._hol_bypass_locked()):
                        continue        # a smaller request jumped ahead
                    stat_set("gen/queue_depth", len(self._queue))
                    stat_set("gen/pages_free", self._pool.free_count)
                    return progressed
                self._queue.popleft()
                gen.pages = matched + self._pool.alloc(need - len(matched))
                gen.shared = len(matched)
                slot = free[0]
                self._slot_gen[slot] = gen
                gen.slot = slot
                if self._ledger is not None:
                    gen.admitted_ts = time.monotonic()
                    self._ledger.book_admission(gen, gen.admitted_ts)
                if self._sched is not None:
                    self._sched.note_admitted(gen)
                gen.prefilling = True
                gen.prefill_pos = len(matched) * P
                gen.prefill_t0 = time.perf_counter()
                self._pt[slot] = 0
                self._pt[slot, :len(gen.pages)] = gen.pages
                self._pt_sync_row_locked(slot)
                if matched:
                    stat_add("gen/prefix_hits")
                    stat_add("gen/prefix_tokens_saved", len(matched) * P)
                stat_set("gen/pages_free", self._pool.free_count)
                stat_set("gen/slots_active",
                         sum(g is not None for g in self._slot_gen))
                stat_set("gen/queue_depth", len(self._queue))
                self._gen_event(gen, "gen/admitted", slot=slot,
                                prompt_len=int(gen.prompt.size),
                                pages=len(gen.pages), shared=gen.shared)
                progressed = True

    # -- scheduler mechanics (FLAGS_gen_sched; never run otherwise) --------
    def _hol_bypass_locked(self) -> bool:
        """The queue head is blocked on pages: scan the plan's bounded
        window past it for a request whose worst case fits the free
        pool RIGHT NOW and rotate it to the front. The scheduler
        re-orders the queue every iteration, so the bypassed head
        returns to the front as soon as pages free up — bounded, not
        starvation. Caller holds the lock; True when a candidate
        moved (the admit loop then retries)."""
        P = self._page_tokens
        limit = min(len(self._queue), self._plan.hol_window + 1)
        for i in range(1, limit):
            g = self._queue[i]
            if g.done:
                continue
            need = -(-(g.prompt.size + g.max_new_tokens - g.folded
                       + self._spec_k) // P)
            if need <= self._pool.free_count:
                del self._queue[i]
                self._queue.appendleft(g)
                stat_add("gen/sched_hol_bypass")
                return True
        return False

    def _preempt_tick(self) -> None:
        """An interactive request heads the queue with every slot busy:
        park the scheduler's chosen victim (strictly lower class, most
        recently admitted) so the next admit tick seats the interactive
        stream. Paged engines only — parking releases pages, and resume
        rides the chunked-prefill path. Loop thread only."""
        if not self._paged:
            return
        # flush the async dispatch lookahead first: no in-flight step
        # may hold a snapshot of a slot this tick is about to clear
        # (their lagged tokens would hit the identity guard anyway, but
        # draining keeps every parked stream's token list final)
        self._drain_pending()
        with self._cond:
            if not self._queue:
                return
            head = self._queue[0]
            if head.done or head.slot is not None:
                return
            if any(g is None for g in self._slot_gen):
                return                  # a slot freed meanwhile
            cands = [(s, g) for s, g in enumerate(self._slot_gen)
                     if g is not None and not g.prefilling
                     and not g.done]
            for _s, victim in self._sched.choose_victims(
                    cands, head.pclass, 1):
                self._park_locked(victim)

    def _park_locked(self, gen: Generation) -> None:
        """Preempt a decoding generation without losing a byte: fold
        the tokens it has emitted into its prompt and advance
        ``rng_skip`` by the same count (one sampling split per emitted
        token — exactly the cross-replica resume contract the
        determinism tests pin), release its slot and pages, and
        re-queue it. Re-admission chunk-prefills the folded prompt —
        the prefix cache turns that into a table rebuild when the pages
        survived — and decode continues byte-identically. Delivered
        tokens stay on ``gen.tokens``; pollers never notice beyond the
        pause. Caller holds the lock."""
        new = np.asarray(gen.tokens[gen.folded:], np.int32)
        if new.size:
            gen.prompt = np.concatenate([gen.prompt, new])
            gen.rng_skip += int(new.size)
            gen.folded = len(gen.tokens)
            gen.dev_ops = None          # PRNG start moved with rng_skip
        gen.prefill_pos = 0
        self._release_slot_locked(gen)
        self._sched.note_parked(gen)
        self._sched.on_enqueue(gen)     # re-tag at current virtual time
        self._queue.append(gen)
        stat_add("gen/preemptions")
        stat_set("gen/queue_depth", len(self._queue))
        self._gen_event(gen, "gen/parked", tokens=len(gen.tokens),
                        folded=int(gen.folded))

    def _page_frame(self, pid: int) -> bytes:
        """Serialize pool page ``pid`` (one device->host fetch per
        cache leaf) into a wire frame. Works for both layouts — the
        int8 quantized pool just has 4 leaves instead of 2."""
        from paddle_tpu.models.generation import serialize_page
        return serialize_page([np.asarray(leaf[pid])
                               for leaf in self._state["cache"]])

    def _kv_demote(self, e: _PrefixEntry) -> None:
        """Prefix-cache eviction hook: publish the victim page to the
        KV store (under its full radix chain key) before the pool
        releases it — eviction demotes instead of dropping."""
        chain = self._prefix.chain_tokens(e)
        if chain is None:
            return
        from paddle_tpu.serving.kvstore import page_chain_keys
        toks = np.frombuffer(b"".join(chain), np.int32)
        key = page_chain_keys(toks, self._page_tokens)[-1]
        if self._kv.contains(key) or self._kv.put(key,
                                                  self._page_frame(e.page)):
            self._kv_demoted += 1
            stat_add("gen/kv_demotions")

    def _kv_publish(self, gen: Generation) -> None:
        """Publish every full prompt page of a finished prefill to the
        store (prefill/'both' tier AND decode tier — whoever computed
        pages shares them; the store's content-addressed put makes
        re-publication a no-op)."""
        from paddle_tpu.serving.kvstore import page_chain_keys
        keys = page_chain_keys(gen.prompt, self._page_tokens)
        for i, key in enumerate(keys):
            if self._kv.contains(key):
                continue
            frame = self._page_frame(gen.pages[i])
            if self._kv.put(key, frame):
                self._kv_published += 1
                stat_add("gen/kv_puts")
                stat_add("gen/kv_put_bytes", len(frame))

    def _kv_admit_fetch(self, gen: Generation,
                        matched: list[int]) -> list[int]:
        """Admission-time store fetch: extend the local radix match
        with pages fetched from the KV store, so the miss becomes a
        transfer instead of a prefill recompute. Fetched pages are
        scattered into the pool host-side and registered in the prefix
        cache (page tables are rehydrated from the page-id list like
        any matched page). Stops at the first miss / corrupt frame /
        page shortage; capped like ``match`` so at least one prompt
        token remains to prefill.

        The store I/O runs with the scheduler lock RELEASED (the
        caller holds it): a slow or dead tier must not freeze pollers,
        cancels, or the watchdog heartbeat. ``self._admitting`` marks
        the generation as busy work for the watchdog; after
        re-acquiring, an epoch change or stuck latch means the pool we
        were admitting into is gone — everything is dropped. Every
        budget overrun, tier failure or corrupt frame degrades the
        remainder of the chain to local prefill recompute
        (byte-identical by construction) and books
        ``gen/kv_fetch_degraded``."""
        from paddle_tpu.models.generation import deserialize_page
        from paddle_tpu.serving.kvstore import page_chain_keys
        import jax.numpy as jnp
        P = self._page_tokens
        cap = (int(gen.prompt.size) - 1) // P
        start = len(matched)
        if start >= cap:
            return []
        t0 = time.perf_counter()
        kv_budget = self._kv_admit_s
        if self._plan is not None:
            # scheduler budget: tighten the fetch window under
            # interactive SLO pressure (the miss degrades to local
            # recompute — byte-identical, just compute instead of I/O)
            kv_budget *= self._plan.kv_scale
        keys = page_chain_keys(gen.prompt, P, limit=cap)
        shapes = [(tuple(pl.shape[1:]), pl.dtype)
                  for pl in self._state["cache"]]
        epoch0 = self._epoch
        self._admitting = gen
        self._cond.release()
        frames: list[tuple[tuple, int]] = []   # (validated leaves, nbytes)
        degraded = False
        try:
            for key in keys[start:]:
                if gen.done or self._stuck or self._stopping:
                    break
                if (kv_budget > 0
                        and time.perf_counter() - t0 > kv_budget):
                    # admission-level budget across the whole chain:
                    # the rest is recompute debt, not a wedge
                    degraded = True
                    stat_add("gen/kv_admit_timeouts")
                    break
                try:
                    frame, deg = self._kv.fetch(key)
                except Exception:
                    frame, deg = None, True
                if frame is None:
                    degraded |= deg
                    break
                try:
                    leaves = deserialize_page(frame)
                except ValueError:
                    # corrupt/truncated store entry: a miss, but a
                    # DEGRADED one — the bytes existed and were bad
                    degraded = True
                    stat_add("gen/kv_corrupt")
                    break
                if (len(leaves) != len(shapes)
                        or any(l.shape != shp or l.dtype != dt
                               for l, (shp, dt) in zip(leaves, shapes))):
                    break                # foreign layout: not our pool
                frames.append((leaves, len(frame)))
        finally:
            self._cond.acquire()
            self._admitting = None
        dt = time.perf_counter() - t0
        if self._goodput is not None:
            self._goodput.note("kv_fetch", dt)
        if degraded:
            self._kv_degraded += 1
            stat_add("gen/kv_fetch_degraded")
        if gen.done or self._epoch != epoch0 or self._stuck:
            # cancelled / watchdog-failed / rebuilt while unlocked: the
            # caller re-evaluates; nothing was alloc'd yet
            return []
        fetched: list[int] = []
        nbytes = 0
        for leaves, flen in frames:
            if self._pool.free_count == 0 and self._prefix.evict(
                    1, self._pool, demote=self._kv_demote) == 0:
                break
            pid = self._pool.alloc(1)[0]
            self._state["cache"] = tuple(
                pl.at[pid].set(jnp.asarray(l)) for pl, l
                in zip(self._state["cache"], leaves))
            fetched.append(pid)
            nbytes += flen
        if fetched:
            # register the fetched chain so the NEXT admission is a
            # local radix hit; insert gives the cache its +1 ref, the
            # alloc above is the generation's ref — same accounting as
            # a matched page
            cov = start + len(fetched)
            self._prefix.insert(gen.prompt[:cov * P], matched + fetched,
                                self._pool)
            self._kv_fetched_pages += len(fetched)
            self._kv_fetched_bytes += nbytes
            stat_add("gen/kv_hits")
            stat_add("gen/kv_fetch_pages", len(fetched))
            stat_add("gen/kv_fetch_bytes", nbytes)
            stat_add("gen/kv_fetch_tokens_saved", len(fetched) * P)
        else:
            stat_add("gen/kv_miss")
        return fetched

    def _gen_dev_ops(self, gen: Generation, jax, jnp) -> tuple:
        """Per-request device operands (starting PRNG key with
        ``rng_skip`` applied, temperature/top_k/top_p scalars), built
        once and cached on the generation — they never change for its
        lifetime, so chunked prefill stops re-materializing four host
        arrays per chunk."""
        if gen.dev_ops is None:
            key = jax.random.PRNGKey(gen.seed)
            if gen.rng_skip:
                from paddle_tpu.models.generation import advance_key
                key = advance_key(key, gen.rng_skip)
            gen.dev_ops = (key,
                           jnp.asarray(gen.temperature, jnp.float32),
                           jnp.asarray(gen.top_k, jnp.int32),
                           jnp.asarray(gen.top_p, jnp.float32))
        return gen.dev_ops

    def _prefill_tick(self) -> bool:
        """Advance every prefilling slot by ONE chunk (then the loop
        runs a decode step — chunked prefill interleaves with decode
        instead of stalling every active stream for a full-prompt
        prefill). The final chunk samples the first token and flips the
        slot into decode."""
        import jax
        import jax.numpy as jnp

        with self._cond:
            work = [(s, g) for s, g in enumerate(self._slot_gen)
                    if g is not None and g.prefilling]
            pt_dev = None if not work else self._pt_device_locked(jnp)
            epoch0 = self._epoch
        ticked = False
        for slot, gen in work:
            T0 = gen.prompt.size
            a = gen.prefill_pos
            C = self._prefill_chunk if self._prefill_chunk > 0 else T0 - a
            if self._plan is not None and self._plan.prefill_chunk:
                # scheduler budget: clamp this iteration's chunk so a
                # long batch prefill cannot monopolize the loop while
                # interactive work waits
                C = min(C, self._plan.prefill_chunk)
            b = min(T0, a + C)
            final = b >= T0
            smax = self._maxp * self._page_tokens
            # cap the padded length so the traced write window stays in
            # bounds (dynamic_update_slice clamps its start — an
            # overflowing pad window would silently shift real tokens)
            bucket = min(self._bucket(b - a), smax - a)
            padded = np.full((bucket,), self._pad, np.int32)
            padded[:b - a] = gen.prompt[a:b]
            key, temp, top_k, top_p = self._gen_dev_ops(gen, jax, jnp)
            t0 = time.perf_counter()
            try:
                with self._gen_span(gen, "gen/prefill_chunk", slot=slot,
                                    index=a, tokens=b - a, final=final):
                    _fault.inject("engine.prefill")
                    self._state, tok0 = self._prefill_fn(
                        self._state, pt_dev,
                        jnp.asarray(slot, jnp.int32), jnp.asarray(padded),
                        jnp.asarray(a, jnp.int32),
                        jnp.asarray(b - a, jnp.int32), key,
                        temp, top_k, top_p)
                    tok0 = int(tok0) if final else None
            except Exception as e:       # a prefill trap implicates
                self._note_trap([gen], e, exact=True)  # exactly this one
                raise
            dt = time.perf_counter() - t0
            observe("gen/prefill_chunk_s", dt)
            compiled = self._note_compile("paged_prefill", bucket, dt)
            if self._goodput is not None:
                self._goodput.note("recompile" if compiled else "prefill",
                                   dt)
            if self._ledger is not None:
                gen.chip_s += dt
            self._last_beat = time.monotonic()
            self._consec_traps = 0       # real device work succeeded
            if self._epoch != epoch0:
                raise _EpochChanged("prefill chunk outlived the "
                                    "watchdog deadline")
            ticked = True
            with self._cond:
                if self._slot_gen[slot] is not gen:
                    continue                # cancelled/reaped mid-chunk
                gen.prefill_pos = b
                if not final:
                    continue
                gen.prefilling = False
                observe("gen/prefill_s",
                        time.perf_counter() - gen.prefill_t0)
                if self._prefix is not None:
                    self._prefix.insert(gen.prompt, gen.pages, self._pool)
                if self._kv is not None:
                    self._kv_publish(gen)
                gen.tokens.append(tok0)
                if self._ledger is not None and gen.first_tok_ts == 0.0:
                    gen.first_tok_ts = time.monotonic()
                if gen.folded == 0:
                    # TTFT = enqueue -> first token (queue wait
                    # included): the latency an interactive SLO is
                    # actually about, and the signal the serving
                    # control plane autoscales on. A parked stream's
                    # resume-prefill is NOT a first token — its TTFT
                    # was observed before the preemption.
                    observe("gen/ttft_s", time.monotonic() - gen.created)
                    if self._sched is not None and gen.tenant:
                        # per-tenant split: the fairness input
                        # MetricsHub.burn_rates(tenant=) reads
                        observe(f"gen/ttft_s/{gen.tenant}",
                                time.monotonic() - gen.created)
                stat_add("gen/tokens")
                if ((gen.eos_token_id is not None
                     and tok0 == gen.eos_token_id)
                        or len(gen.tokens) >= gen.max_new_tokens):
                    gen.done = True
                    if self._ledger is not None:
                        gen.done_ts = time.monotonic()
                    self._gen_event(gen, "gen/retire", reason="complete",
                                    tokens=len(gen.tokens))
                    self._release_slot_locked(gen)
                self._cond.notify_all()
        return ticked

    def _prefill(self, gen: Generation, slot: int) -> None:
        import jax
        import jax.numpy as jnp

        T0 = gen.prompt.size
        bucket = self._bucket(T0)
        padded = np.full((bucket,), self._pad, np.int32)
        padded[:T0] = gen.prompt
        key, temp, top_k, top_p = self._gen_dev_ops(gen, jax, jnp)
        epoch0 = self._epoch
        t0 = time.perf_counter()
        try:
            with self._gen_span(gen, "gen/prefill", slot=slot,
                                prompt_len=T0, bucket=bucket):
                _fault.inject("engine.prefill")
                self._state, tok0 = self._prefill_fn(
                    self._state, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded), jnp.asarray(T0, jnp.int32), key,
                    temp, top_k, top_p)
                tok0 = int(tok0)
        except Exception as e:           # a prefill trap implicates
            self._note_trap([gen], e, exact=True)     # exactly this one
            raise
        dt = time.perf_counter() - t0
        observe("gen/prefill_s", dt)
        compiled = self._note_compile("prefill", bucket, dt)
        if self._goodput is not None:
            self._goodput.note("recompile" if compiled else "prefill", dt)
        if self._ledger is not None:
            gen.chip_s += dt
        self._last_beat = time.monotonic()
        self._consec_traps = 0           # real device work succeeded
        if self._epoch != epoch0:
            raise _EpochChanged("prefill outlived the watchdog deadline")
        with self._cond:
            if self._slot_gen[slot] is not gen:   # cancelled mid-prefill
                return
            gen.tokens.append(tok0)
            if self._ledger is not None:
                gen.first_tok_ts = time.monotonic()
            observe("gen/ttft_s", time.monotonic() - gen.created)
            if self._sched is not None and gen.tenant:
                observe(f"gen/ttft_s/{gen.tenant}",
                        time.monotonic() - gen.created)
            stat_add("gen/tokens")
            if ((gen.eos_token_id is not None
                 and tok0 == gen.eos_token_id)
                    or len(gen.tokens) >= gen.max_new_tokens):
                gen.done = True
                if self._ledger is not None:
                    gen.done_ts = time.monotonic()
                self._gen_event(gen, "gen/retire", reason="complete",
                                tokens=len(gen.tokens))
                self._release_slot_locked(gen)
            self._cond.notify_all()

    def _decode_step(self, jnp) -> bool:
        if self._pending and self._spec_k > 0:
            # speculative drafting (and the occupancy-shed decision)
            # reads host-side context — flush the dispatch lookahead
            # first so drafts see up-to-date tokens and slots
            self._drain_pending()
        with self._cond:
            stepped = [(s, g) for s, g in enumerate(self._slot_gen)
                       if g is not None and not g.prefilling]
            if not stepped and not self._pending:
                return False
            active = np.zeros((self.slots,), bool)
            for s, _ in stepped:
                active[s] = True
            pt_dev = (self._pt_device_locked(jnp)
                      if self._paged and stepped else None)
            epoch0 = self._epoch
            specable: list[tuple[int, np.ndarray, int]] = []
            spec_k = self._spec_k
            if (spec_k > 0 and self._plan is not None
                    and self._plan.spec_budget is not None):
                # scheduler budget: 0 sheds speculation outright this
                # iteration (interactive work is waiting — the verify
                # step's extra width would delay it); otherwise a cap
                spec_k = min(spec_k, self._plan.spec_budget)
            if spec_k > 0:
                # load-adaptive shedding: above the occupancy threshold
                # batched decode already fills the device — speculative
                # FLOPs would only starve co-tenant slots, so the whole
                # iteration falls back to the plain fused step
                occ = (sum(g is not None for g in self._slot_gen)
                       / self.slots)
                if occ <= self._spec_shed:
                    specable = [
                        (s,
                         np.concatenate(
                             [g.prompt,
                              np.asarray(g.tokens, np.int32)]),
                         min(spec_k,
                             g.max_new_tokens - len(g.tokens) - 1))
                        for s, g in stepped]
        if not stepped:
            # nothing new to dispatch: drain the lagged in-flight steps
            # so their retirements land and pages free up
            self._drain_pending()
            return True
        use_spec = False
        if specable:
            # drafting happens OUTSIDE the lock (ngram is host-side
            # numpy; draft-model lookahead is its own compiled call)
            dlens = np.zeros((self.slots,), np.int32)
            drafts = np.zeros((self.slots, self._spec_k), np.int32)
            for s, ctx, cap in specable:
                if cap <= 0:
                    continue       # last token due: nothing to verify
                d = self._propose(ctx, cap)
                if d.size:
                    dlens[s] = d.size
                    drafts[s, :d.size] = d
            # no slot produced a draft -> the plain step is strictly
            # cheaper (width 1 vs K+1) and byte-identical
            use_spec = bool(dlens.any())
        lookahead = self._async_depth > 0 and not use_spec
        t0 = time.perf_counter()
        try:
            with _trace.span("gen/decode_step", active=len(stepped),
                             spec=int(use_spec)):
                _fault.inject("engine.decode_step")
                if use_spec:
                    with _trace.span("gen/spec_verify",
                                     drafted=int(dlens.sum())):
                        args = (pt_dev,) if self._paged else ()
                        self._state, out, emit = self._spec_step(
                            self._state, *args, jnp.asarray(active),
                            jnp.asarray(drafts), jnp.asarray(dlens))
                        out = np.asarray(out)
                        emit = np.asarray(emit)
                else:
                    args = (pt_dev,) if self._paged else ()
                    self._state, toks = self._step(
                        self._state, *args, jnp.asarray(active))
                    if not lookahead:
                        toks = np.asarray(toks)
        except Exception as e:
            # the fused step shares one compiled call: every stepped
            # generation is implicated (co-tenant counts — see
            # _note_trap's threshold note)
            self._note_trap([g for _, g in stepped], e)
            raise
        dt = time.perf_counter() - t0
        observe("gen/decode_step_s", dt)
        if use_spec:
            observe("gen/spec_verify_s", dt)
        compiled = self._note_compile(
            "spec_step" if use_spec
            else ("paged_step" if self._paged else "step"), 0, dt)
        if self._goodput is not None:
            self._goodput.note(
                "recompile" if compiled
                else ("spec_verify" if use_spec else "decode"), dt)
        # chip-second attribution: one fused step serves every stepped
        # slot — split its device wall evenly across them
        chip_share = (dt / len(stepped)
                      if self._ledger is not None else 0.0)
        self._last_beat = time.monotonic()
        if lookahead:
            # defer the blocking token readback (gen_async_depth): the
            # autoregressive chain feeds itself on device, so the next
            # loop iteration dispatches step i+1 before step i's tokens
            # come back; delivery/retirement bookkeeping runs against
            # the lagged tokens when the entry drains — <= depth steps
            # late, safe because post-EOS steps write only pads.
            # _consec_traps is NOT reset here: only the readback in
            # _finish_step proves the device work actually ran.
            self._pending.append((stepped, toks, epoch0, chip_share))
            while len(self._pending) > self._async_depth:
                self._drain_pending(1)
            if self.step_wait_s > 0:
                time.sleep(self.step_wait_s)
                if self._goodput is not None:
                    self._goodput.note("admission_idle",
                                       self.step_wait_s)
            return True
        self._consec_traps = 0           # real device work succeeded
        if self._epoch != epoch0:
            raise _EpochChanged("decode step outlived the watchdog "
                                "deadline")
        # per-iteration stream sampling (FLAGS_trace_sample, hard-off):
        # every Nth emitted token of an id-carrying stream records a
        # gen/decode_sample event — affordable per-iteration visibility
        sample_n = (int(flag("trace_sample"))
                    if _trace._ACTIVE is not None else 0)
        with self._cond:
            emitted = 0
            for s, gen in stepped:
                if self._slot_gen[s] is not gen:   # cancelled mid-step
                    continue
                if self._ledger is not None:
                    gen.chip_s += chip_share
                if use_spec:
                    n = int(emit[s])
                    new = [int(t) for t in out[s, :n]]
                    dlen = int(dlens[s])
                    if dlen:
                        acc = n - 1
                        gen.spec_proposed += dlen
                        gen.spec_accepted += acc
                        self._spec_proposed += dlen
                        self._spec_accepted += acc
                        stat_add("gen/spec_proposed", dlen)
                        stat_add("gen/spec_accepted", acc)
                        stat_add("gen/spec_rejected", dlen - acc)
                        observe("gen/spec_accept_len", float(acc))
                        if sample_n > 0:
                            self._gen_event(gen, "gen/spec_accept",
                                            slot=s, proposed=dlen,
                                            accepted=acc)
                else:
                    new = [int(toks[s])]
                for tok in new:
                    gen.tokens.append(tok)
                    emitted += 1
                    if sample_n > 0 and len(gen.tokens) % sample_n == 0:
                        self._gen_event(gen, "gen/decode_sample", slot=s,
                                        token_index=len(gen.tokens))
                    if ((gen.eos_token_id is not None
                         and tok == gen.eos_token_id)
                            or len(gen.tokens) >= gen.max_new_tokens):
                        # accepted tokens past EOS are discarded on the
                        # host; the device state past this point is
                        # garbage but the slot is released right here
                        gen.done = True
                        if self._ledger is not None:
                            gen.done_ts = time.monotonic()
                        self._gen_event(gen, "gen/retire",
                                        reason="complete",
                                        tokens=len(gen.tokens))
                        self._release_slot_locked(gen)
                        break
            if use_spec:
                self._spec_verify_steps += 1
            self._emit_total += emitted
            self._decode_iters += 1
            if emitted:
                stat_add("gen/tokens", emitted)
            self._cond.notify_all()
        if self.step_wait_s > 0:
            time.sleep(self.step_wait_s)
            if self._goodput is not None:
                # deliberate pacing gap: idle by configuration, not work
                self._goodput.note("admission_idle", self.step_wait_s)
        return True

    # -- async dispatch lookahead (gen_async_depth) ------------------------
    def _drain_pending(self, n: int | None = None) -> None:
        """Retire deferred readbacks, oldest first: block on each
        entry's device tokens and run the delivery/retirement
        bookkeeping the sync loop does inline. ``n`` bounds how many
        entries drain (None = all). Loop thread only; the reset paths
        may clear the deque concurrently, hence the guarded pop."""
        while self._pending and (n is None or n > 0):
            try:
                entry = self._pending.popleft()
            except IndexError:       # cleared under our feet (reset)
                return
            self._finish_step(*entry)
            if n is not None:
                n -= 1

    def _finish_step(self, stepped, toks_dev, epoch0,
                     chip_share) -> None:
        """Second half of a lookahead decode step: the now-explicit
        blocking readback — measured and booked as ``host_gather``
        instead of swept in by ``tick`` — followed by the same
        bookkeeping as the sync path. Deferred device errors surface
        HERE (np.asarray is where XLA delivers them) and implicate the
        entry's generations exactly like a sync trap. A slot retired
        or reassigned by an earlier entry is skipped by the identity
        guard, so lagged post-EOS tokens are never delivered."""
        t0 = time.perf_counter()
        try:
            toks = np.asarray(toks_dev)
        except Exception as e:
            self._note_trap([g for _, g in stepped], e)
            raise
        if self._goodput is not None:
            self._goodput.note("host_gather", time.perf_counter() - t0)
        self._last_beat = time.monotonic()
        self._consec_traps = 0           # real device work succeeded
        if self._epoch != epoch0:
            # the watchdog failed this entry's generations while it was
            # in flight — its tokens are garbage; the loop's stuck
            # latch forces the rebuild/break decision
            return
        sample_n = (int(flag("trace_sample"))
                    if _trace._ACTIVE is not None else 0)
        with self._cond:
            emitted = 0
            for s, gen in stepped:
                if self._slot_gen[s] is not gen:   # retired/cancelled
                    continue                       # by an earlier entry
                if self._ledger is not None:
                    gen.chip_s += chip_share
                tok = int(toks[s])
                gen.tokens.append(tok)
                emitted += 1
                if sample_n > 0 and len(gen.tokens) % sample_n == 0:
                    self._gen_event(gen, "gen/decode_sample", slot=s,
                                    token_index=len(gen.tokens))
                if ((gen.eos_token_id is not None
                     and tok == gen.eos_token_id)
                        or len(gen.tokens) >= gen.max_new_tokens):
                    gen.done = True
                    if self._ledger is not None:
                        gen.done_ts = time.monotonic()
                    self._gen_event(gen, "gen/retire",
                                    reason="complete",
                                    tokens=len(gen.tokens))
                    self._release_slot_locked(gen)
            self._emit_total += emitted
            self._decode_iters += 1
            if emitted:
                stat_add("gen/tokens", emitted)
            self._cond.notify_all()
