"""Serving control plane: multi-model multiplexing, SLO-driven
autoscaling, and sticky-drain scale-down.

Reference role: the fleet-management half of Paddle Serving — a config
names N models and a replica count, a manager process keeps that many
predictor replicas alive, loads/unloads models on them, and resizes the
fleet against load. paddle_tpu shipped the *mechanisms* over PRs 2–6
(universal ``health`` with slot/page occupancy + mergeable histograms,
``RoutedClient`` live membership, broadcast ``load_model``, graceful
``drain``) but nothing *decided* anything. This module is the decider —
the layer Orca (OSDI '22) and vLLM (SOSP '23) both assume above the
engine:

- **Multi-model multiplexing** — :meth:`ServingController.register_model`
  builds a registry larger than any one replica keeps resident. A
  request for a cold model faults it in (broadcast ``load_model``);
  the reconcile loop reads the per-model stats every replica now ships
  in ``health`` (infer count, last-used, approx resident bytes) and
  LRU-evicts past the ``control_warm_models`` warm-tier capacity with
  the new ``unload_model`` wire op. ``register_model(..., warm=True)``
  pins a model against eviction.
- **SLO-driven autoscaling** — each ``control_interval_s`` the loop
  feeds every replica's health snapshot into a
  :class:`~paddle_tpu.serving.metrics.MetricsHub` (the windowed
  in-memory fleet TSDB) and reads the fleet's signals back out of it:
  queued generations and slot occupancy from ``health``'s
  ``generators`` section, mean wire in-flight, and the ``gen/ttft_s``
  **multi-window SLO burn rate** against ``control_target_ttft_s`` —
  TTFT pressure requires BOTH the fast (``control_burn_fast_ticks``)
  and slow (``control_burn_slow_ticks``) windows to burn error budget
  (``control_slo_budget``) faster than ``control_burn_threshold``, the
  standard two-window page condition that replaces the old single-tick
  raw-p99 breach check (noisy by construction: one slow request per
  tick paged).  Sustained pressure (``control_breach_ticks``
  consecutive breaching ticks) scales up through a
  :class:`ReplicaSpawner`; sustained idleness (``control_idle_ticks``)
  scales down; ``control_cooldown_s`` spaces scale events. Hysteresis
  + cooldown make the loop flap-proof by construction.  Every scale
  decision records its burn-rate evidence in the
  :class:`ControlDecision` signals.
- **Sticky-drain scale-down** — the victim is ``cordon``\\ ed (no new
  routed or session picks; pooled connections stay open), the controller
  watches its health until in-flight requests hit zero and every
  generation is *delivered* (done AND its final poll answered — the
  engine's ``undelivered`` stat), then stops it through the spawner and
  removes the membership. In-flight session-pinned generations run to
  completion on the replica holding their KV state: zero lost idempotent
  requests, zero ``GenerationFailed`` on a clean scale event. A drain
  that outlives ``control_drain_s`` is forced — counted and logged,
  never silent.

Every action is a typed :class:`ControlDecision` (action, reason, the
signal snapshot it was computed from) kept in a ring buffer
(:meth:`ServingController.decisions`) — every scale event is
explainable after the fact.

Defaults are hard-off (the ``FLAGS_trace`` pattern): with
``control_max_replicas=0`` the loop never scales, with
``control_warm_models=0`` it never evicts, and nothing in the serving
data path reads any ``control_*`` flag — a fleet without a controller
is byte-identical to the PR-6 state.

Spawner hardening (``control_spawn_breaker``, hard-off): consecutive
``ReplicaSpawner`` failures — a poisoned artifact crash-looping
``replace``, an exhausted quota failing scale-up — open a circuit
breaker with exponential backoff (``control_spawn_backoff_s`` base,
doubling, capped at 32x): the controller records a ``spawn_breaker``
decision instead of calling the spawner, lets one half-open trial
through when the backoff elapses, and closes the breaker on the first
success. The fleet degrades predictably instead of hot-looping spawns.

High availability (``FLAGS_control_ha_lease_dir``, hard-off): N
controllers contend for a file-based leader lease (``serving/ha.py``)
on a shared directory or ``ptfs://`` root — exactly one acts per tick,
standbys take over within one TTL. The leader write-ahead journals
every fleet mutation (spawn/adopt/remove/register_model/drain), so a
newly-elected leader replays to the exact managed set and registry,
probes journaled endpoints over the never-shed ``health`` op, ADOPTS
the live ones (streams untouched), replaces the dead, and resumes any
in-progress sticky drain. Every spawner action is fenced on the
leader's (holder, term): a deposed leader's queued spawn/stop raises
the typed ``StaleEpochError`` and is recorded as a ``fenced`` decision,
never executed. With the flag empty (the default) none of this exists:
no lease probes, no journal bytes, no extra thread — byte-identical to
the single-controller build.

Observability: ``control/replicas`` gauge; ``control/ticks`` /
``control/scale_ups`` / ``control/scale_downs`` / ``control/replaced`` /
``control/model_evictions`` / ``control/model_faults`` /
``control/drain_forced`` / ``control/spawn_failures`` /
``control/spawn_breaker_opened`` / ``control/spawn_skipped`` counters;
``control/ha_acquired`` / ``control/ha_renewals`` /
``control/ha_takeovers`` / ``control/ha_adopted`` /
``control/ha_deposed`` / ``control/ha_fenced`` /
``control/ha_standby_ticks`` / ``control/ha_drains_resumed`` /
``control/ha_journal_records`` / ``control/ha_journal_errors`` /
``control/ha_compactions`` / ``control/ha_lost_spawns`` counters;
``control/drain_s`` histogram; ``control/tick`` / ``control/scale_up`` /
``control/drain`` spans.
"""

from __future__ import annotations

import os
import random as _random_mod
import signal as _signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from paddle_tpu.core import fault as _fault
from paddle_tpu.core import trace as _trace
from paddle_tpu.core.flags import flag
from paddle_tpu.core.logging import get_logger
from paddle_tpu.core.monitor import observe, stat_add, stat_set
from paddle_tpu.io.serving import (
    InferenceClient, InferenceServer, ModelBusyError,
)
from paddle_tpu.serving.ha import (
    ControlService, FencedSpawner, FleetJournal, FleetState, LeaderLease,
    StaleEpochError,
)
from paddle_tpu.serving.metrics import MetricsHub
from paddle_tpu.serving.router import RoutedClient

__all__ = ["ServingController", "ControlDecision", "ReplicaSpawner",
           "InProcSpawner", "SubprocessSpawner"]

_log = get_logger()

_jitter_rng = _random_mod.Random()


def _jittered(base: float) -> float:
    """U[0.9, 1.1) x base — decorrelates N controllers' (and routers')
    probe cadence so standbys don't synchronize their health scrapes
    into a thundering herd on the leader's fleet (the PR-8 shed-jitter
    idiom, tighter band: a cadence, not a backoff)."""
    return base * (0.9 + 0.2 * _jitter_rng.random())


@dataclass
class ControlDecision:
    """One explainable control-plane action. ``signals`` is the fleet
    snapshot the decision was computed from (queue depth, occupancy,
    TTFT p99, replica count, ...) — JSON-safe, so decisions export
    straight into logs/benches."""

    action: str                  # scale_up | scale_down | hold | evict |
    #                              fault_in | replace | spawn_failed |
    #                              spawn_breaker | adopt | fenced |
    #                              deposed | drain_resume
    reason: str
    endpoint: str | None = None
    clean: bool = True           # drains: finished inside the deadline?
    ts: float = 0.0
    signals: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return {"action": self.action, "reason": self.reason,
                "endpoint": self.endpoint, "clean": self.clean,
                "ts": self.ts, "signals": dict(self.signals)}


class ReplicaSpawner:
    """Hook through which the controller creates/destroys replicas —
    the only part of the control plane that knows HOW a replica runs
    (in-process for tests/bench, subprocess for chaos/isolation, a k8s
    client in a real deployment). ``spawn`` returns the new replica's
    ``host:port`` once it is accepting; ``stop`` shuts one down with a
    graceful-drain budget."""

    def spawn(self) -> str:                      # pragma: no cover
        raise NotImplementedError

    def stop(self, endpoint: str, drain_s: float = 0.0) -> None:
        raise NotImplementedError                # pragma: no cover

    def adopt(self, endpoint: str, pid: int | None = None) -> None:
        """Take responsibility for an already-running replica this
        spawner did not create — a newly-elected HA leader adopting the
        previous leader's fleet from the journal. Default: nothing to
        track (a k8s spawner would look the pod up by endpoint)."""

    def pid_of(self, endpoint: str) -> int | None:
        """OS pid of a replica this spawner tracks (journaled so an
        adopting leader can escalate a stop); None when not a process."""
        return None


class InProcSpawner(ReplicaSpawner):
    """Replicas are :class:`~paddle_tpu.io.serving.InferenceServer`
    instances in this process, built by ``factory()`` (which registers
    whatever models/generators a replica of this fleet serves, and may
    pre-warm compiles). The factory may return a started or unstarted
    server. ``kill`` severs one without drain — the chaos path."""

    def __init__(self, factory: Callable[[], InferenceServer]):
        self._factory = factory
        self._lock = threading.Lock()
        self.servers: dict[str, InferenceServer] = {}
        self.adopted: set[str] = set()

    def spawn(self) -> str:
        srv = self._factory()
        if srv._thread is None:          # factory may pre-start
            srv.start()
        with self._lock:
            self.servers[srv.endpoint] = srv
        return srv.endpoint

    def adopt(self, endpoint: str, pid: int | None = None) -> None:
        """An adopted replica has no server object here (it lives in
        another controller's spawner or was started by hand); stop
        falls back to the wire ``stop_server`` op."""
        with self._lock:
            self.adopted.add(endpoint)

    def stop(self, endpoint: str, drain_s: float = 0.0) -> None:
        with self._lock:
            srv = self.servers.pop(endpoint, None)
            adopted = endpoint in self.adopted
            self.adopted.discard(endpoint)
        if srv is not None:
            srv.stop(drain_s=drain_s if drain_s > 0 else None)
        elif adopted:
            try:
                with InferenceClient(endpoint, timeout=5.0,
                                     retries=0) as c:
                    c.stop_server()
            except (ConnectionError, RuntimeError, OSError):
                pass

    def kill(self, endpoint: str) -> None:
        """Hard stop — sockets severed, no drain (a crash, for chaos)."""
        with self._lock:
            srv = self.servers.pop(endpoint, None)
        if srv is not None:
            srv.stop()


class SubprocessSpawner(ReplicaSpawner):
    """Each replica is a separate OS process (its own GIL and XLA
    runtime) running ``python -m paddle_tpu.serving.replica_main`` with
    the given ``name=path`` model artifacts. ``spawn`` blocks until the
    child prints its endpoint; ``stop`` asks it to drain over the wire
    and escalates to terminate/kill; :meth:`kill` SIGKILLs — the
    realistic chaos primitive for "a replica died mid-scale-event"."""

    def __init__(self, models: dict[str, str] | None = None, *,
                 startup_timeout_s: float = 60.0,
                 extra_args: tuple[str, ...] = ()):
        self._models = dict(models or {})
        self._timeout = float(startup_timeout_s)
        self._extra = tuple(extra_args)
        self._lock = threading.Lock()
        self.procs: dict[str, subprocess.Popen] = {}
        self.adopted_pids: dict[str, int | None] = {}

    def spawn(self) -> str:
        cmd = [sys.executable, "-m", "paddle_tpu.serving.replica_main"]
        cmd += [f"{n}={p}" for n, p in self._models.items()]
        cmd += list(self._extra)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
        deadline = time.monotonic() + self._timeout
        endpoint = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("ENDPOINT "):
                endpoint = line.split(None, 1)[1].strip()
                break
        if endpoint is None:
            proc.kill()
            raise RuntimeError(
                "replica subprocess failed to report an endpoint within "
                f"{self._timeout}s (exit={proc.poll()})")
        with self._lock:
            self.procs[endpoint] = proc
        return endpoint

    def adopt(self, endpoint: str, pid: int | None = None) -> None:
        """Track a replica process another controller spawned (the pid
        comes from the HA journal; a newly-elected leader has no Popen
        handle). stop/kill then go over the wire, escalating by pid."""
        with self._lock:
            if endpoint not in self.procs:
                self.adopted_pids[endpoint] = pid

    def pid_of(self, endpoint: str) -> int | None:
        with self._lock:
            proc = self.procs.get(endpoint)
            if proc is not None:
                return proc.pid
            return self.adopted_pids.get(endpoint)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    def stop(self, endpoint: str, drain_s: float = 0.0) -> None:
        with self._lock:
            proc = self.procs.pop(endpoint, None)
            adopted = endpoint in self.adopted_pids
            pid = self.adopted_pids.pop(endpoint, None)
        if proc is None and not adopted:
            return
        try:                             # graceful: wire stop op drains
            with InferenceClient(endpoint, timeout=5.0, retries=0) as c:
                c.stop_server()
        except (ConnectionError, RuntimeError, OSError):
            pass
        if proc is not None:
            try:
                proc.wait(timeout=max(drain_s, 0.0) + 10.0)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            return
        if pid is None:                  # adopted without a pid: the
            return                       # wire stop is all we have
        # adopted: no Popen handle — poll the journaled pid, escalate
        deadline = time.monotonic() + max(drain_s, 0.0) + 10.0
        while time.monotonic() < deadline and self._pid_alive(pid):
            time.sleep(0.1)
        for sig in (_signal.SIGTERM, _signal.SIGKILL):
            if not self._pid_alive(pid):
                return
            try:
                os.kill(pid, sig)
            except OSError:
                return
            time.sleep(0.5)

    def kill(self, endpoint: str) -> None:
        """SIGKILL the replica process — no drain, no goodbye."""
        with self._lock:
            proc = self.procs.pop(endpoint, None)
            pid = self.adopted_pids.pop(endpoint, None)
        if proc is not None:
            proc.kill()
            proc.wait()
        elif pid is not None:
            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                pass


class ServingController:
    """The fleet manager: owns a managed replica set (created through
    ``spawner``), a model registry bigger than any replica's warm tier,
    and the reconcile loop that turns health signals into scale/evict
    decisions.

    Every knob defaults to its ``control_*`` flag (the
    ``GenerationEngine`` pattern); with the flag defaults the controller
    is inert — ``max_replicas=0`` disables autoscaling,
    ``warm_models=0`` disables eviction, and ``interval_s<=0`` disables
    the background thread entirely (tests drive :meth:`tick` manually).
    ``endpoints`` adopts existing replicas into routing as *unmanaged*
    members: they receive traffic and count toward capacity but are
    never scaled down or stopped.

    Manual overrides — :meth:`scale_to` / :meth:`scale_down` — skip
    hysteresis and cooldown but use the same sticky-drain machinery, so
    an operator-initiated scale-down is exactly as lossless as an
    automatic one.
    """

    def __init__(self, spawner: ReplicaSpawner, *,
                 router: RoutedClient | None = None,
                 endpoints: tuple[str, ...] | list[str] = (),
                 interval_s: float | None = None,
                 warm_models: int | None = None,
                 min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 target_ttft_s: float | None = None,
                 queue_high: float | None = None,
                 occupancy_high: float | None = None,
                 occupancy_low: float | None = None,
                 inflight_high: float | None = None,
                 breach_ticks: int | None = None,
                 idle_ticks: int | None = None,
                 cooldown_s: float | None = None,
                 drain_s: float | None = None,
                 spawn_breaker: int | None = None,
                 spawn_backoff_s: float | None = None,
                 slo_budget: float | None = None,
                 burn_fast_ticks: int | None = None,
                 burn_slow_ticks: int | None = None,
                 burn_threshold: float | None = None,
                 ha_lease_dir: str | None = None,
                 ha_lease_ttl_s: float | None = None,
                 ha_holder: str | None = None,
                 decisions_max: int = 256):
        def _f(v, name):
            return flag(name) if v is None else v

        self._spawner = spawner
        self._own_router = router is None
        self._router = router if router is not None else RoutedClient()
        self.interval_s = float(_f(interval_s, "control_interval_s"))
        self.warm_models = int(_f(warm_models, "control_warm_models"))
        self.min_replicas = int(_f(min_replicas, "control_min_replicas"))
        self.max_replicas = int(_f(max_replicas, "control_max_replicas"))
        self.target_ttft_s = float(_f(target_ttft_s,
                                      "control_target_ttft_s"))
        self.queue_high = float(_f(queue_high, "control_queue_high"))
        self.occupancy_high = float(_f(occupancy_high,
                                       "control_occupancy_high"))
        self.occupancy_low = float(_f(occupancy_low,
                                      "control_occupancy_low"))
        self.inflight_high = float(_f(inflight_high,
                                      "control_inflight_high"))
        self.breach_ticks = int(_f(breach_ticks, "control_breach_ticks"))
        self.idle_ticks = int(_f(idle_ticks, "control_idle_ticks"))
        self.cooldown_s = float(_f(cooldown_s, "control_cooldown_s"))
        self.drain_s = float(_f(drain_s, "control_drain_s"))
        self.spawn_breaker = int(_f(spawn_breaker,
                                    "control_spawn_breaker"))
        self.spawn_backoff_s = float(_f(spawn_backoff_s,
                                        "control_spawn_backoff_s"))
        self.slo_budget = float(_f(slo_budget, "control_slo_budget"))
        self.burn_fast_ticks = int(_f(burn_fast_ticks,
                                      "control_burn_fast_ticks"))
        self.burn_slow_ticks = int(_f(burn_slow_ticks,
                                      "control_burn_slow_ticks"))
        self.burn_threshold = float(_f(burn_threshold,
                                       "control_burn_threshold"))
        # the windowed fleet TSDB every tick's health scrape feeds; all
        # latency/rate signals (and the burn-rate pressure check) read
        # from it instead of ad-hoc previous-snapshot bookkeeping
        self._hub = MetricsHub(fast_ticks=self.burn_fast_ticks,
                               slow_ticks=self.burn_slow_ticks)
        # spawn circuit-breaker state: consecutive failures and the
        # monotonic instant before which the spawner must not be called
        self._spawn_fails = 0
        self._spawn_open_until = 0.0

        self._lock = threading.RLock()
        self._registry: dict[str, dict[str, Any]] = {}   # name -> spec
        self._managed: set[str] = set()
        self._decisions: deque[ControlDecision] = deque(
            maxlen=max(int(decisions_max), 1))
        self._breach = 0
        self._idle = 0
        self._last_scale = 0.0           # monotonic; 0 = never
        self._unreachable: dict[str, int] = {}   # ep -> consecutive ticks
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False
        # --- control-plane HA (FLAGS_control_ha_lease_dir, hard-off):
        # with the flag empty nothing below exists — no lease file IO,
        # no journal, no fencing wrapper, and tick() never gates
        self.ha_lease_dir = str(_f(ha_lease_dir,
                                   "control_ha_lease_dir") or "")
        self._lease: LeaderLease | None = None
        self._journal: FleetJournal | None = None
        self._service: ControlService | None = None
        self._draining: str | None = None
        if self.ha_lease_dir:
            self._lease = LeaderLease(
                self.ha_lease_dir,
                ttl_s=float(_f(ha_lease_ttl_s, "control_ha_lease_ttl_s")),
                holder=str(_f(ha_holder, "control_ha_holder") or "")
                or None)
            self._journal = FleetJournal(
                self.ha_lease_dir,
                compact_records=int(flag("control_ha_compact_records")))
            self._spawner = FencedSpawner(spawner, self._lease)
        for ep in endpoints:
            self._router.add_endpoint(ep)

    # -- model registry / multiplexing ------------------------------------
    def register_model(self, name: str, path: str,
                       warm: bool = False) -> None:
        """Add an artifact to the fleet's model registry. ``warm=True``
        pins it: loaded on every replica (now and at every spawn) and
        never LRU-evicted. Cold models load on first demand
        (:meth:`infer` faults them in) and live under the
        ``warm_models`` residency cap."""
        with self._lock:
            self._registry[name] = {"path": path, "warm": bool(warm)}
        self._journal_rec("register_model", name=name, path=path,
                          warm=bool(warm))
        if warm:
            try:
                self._router.load_model(name, path)
            except (ConnectionError, RuntimeError, OSError):
                pass                     # no replicas yet: loads at spawn

    def registered_models(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {n: dict(s) for n, s in self._registry.items()}

    def load_model(self, name: str) -> None:
        """Broadcast-load a registered model on every healthy
        non-cordoned replica (the cold→warm fault-in)."""
        with self._lock:
            spec = self._registry.get(name)
        if spec is None:
            raise KeyError(f"model {name!r} not registered with the "
                           f"controller; registered: "
                           f"{sorted(self._registry)}")
        self._router.load_model(name, spec["path"])
        stat_add("control/model_faults")

    def infer(self, model: str, *inputs):
        """Routed infer with cold-model fault-in: an unknown-model
        rejection loads the registered artifact fleet-wide, enforces the
        warm-tier cap, and retries once. The steady-state hot path is
        exactly ``RoutedClient.infer`` — one extra exception handler,
        zero extra round-trips."""
        try:
            return self._router.infer(model, *inputs)
        except RuntimeError as e:
            with self._lock:
                registered = model in self._registry
            if "no model" not in str(e) or not registered:
                raise
        self._record(ControlDecision(
            action="fault_in", ts=time.time(),
            reason=f"cold model {model!r} demanded; loading fleet-wide"))
        self.load_model(model)
        if self.warm_models > 0:
            # the demanded model is exempt from its own fault-in sweep —
            # evicting it again before the retry would livelock
            self._evict_over_capacity(self._router.health(),
                                      protect=frozenset((model,)))
        return self._router.infer(model, *inputs)

    def _evict_over_capacity(self, healths: dict[str, dict],
                             protect: frozenset[str] = frozenset()
                             ) -> int:
        """Per replica: unload least-recently-used unpinned models past
        the warm-tier cap (data from the health ``models`` section). A
        model busy in a replica's batcher is skipped this round — the
        typed refusal is the point, eviction retries next tick."""
        evicted = 0
        with self._lock:
            pinned = {n for n, s in self._registry.items() if s["warm"]}
        pinned |= protect
        cap = self.warm_models
        for ep, doc in healths.items():
            models = doc.get("models") if isinstance(doc, dict) else None
            if not models or doc.get("status") != "ok":
                continue
            over = len(models) - cap
            if over <= 0:
                continue
            lru = sorted((n for n in models if n not in pinned),
                         key=lambda n: models[n].get("last_used_ts", 0.0))
            for name in lru[:over]:
                try:
                    if self._client_for(ep).unload_model(name):
                        evicted += 1
                        stat_add("control/model_evictions")
                        self._record(ControlDecision(
                            action="evict", endpoint=ep, ts=time.time(),
                            reason=f"warm tier over capacity ({len(models)}"
                                   f" resident > {cap}); LRU {name!r} "
                                   f"idle {models[name].get('idle_s', 0):.1f}s"))
                except ModelBusyError:
                    continue             # in-flight work wins; next tick
                except (ConnectionError, RuntimeError, OSError):
                    continue
        return evicted

    # -- fleet views -------------------------------------------------------
    @property
    def router(self) -> RoutedClient:
        """The routed client fronting the managed fleet (share it with
        application traffic — the controller reads the same membership
        it steers)."""
        return self._router

    @property
    def hub(self) -> MetricsHub:
        """The windowed fleet TSDB the tick loop feeds (read-only use:
        dashboards, tests, and chaos checks query it directly)."""
        return self._hub

    def replicas(self) -> list[dict]:
        """Router membership annotated with who manages each replica."""
        with self._lock:
            managed = set(self._managed)
        return [dict(m, managed=m["endpoint"] in managed)
                for m in self._router.members()]

    def decisions(self) -> list[dict]:
        """The decision ring buffer, oldest first — every scale/evict/
        replace event with the signals it was computed from."""
        with self._lock:
            return [d.as_dict() for d in self._decisions]

    def control_dump(self, last: int | None = None) -> dict[str, Any]:
        """The wire-shaped controller introspection doc served by
        :class:`~paddle_tpu.serving.ha.ControlService`: the decision
        ring (optionally the last N), the managed set and registry, and
        the leader/term block when HA is on — decisions no longer die
        with the controller process (``tools/obs_dump.py --control``)."""
        with self._lock:
            ds = [d.as_dict() for d in self._decisions]
            managed = sorted(self._managed)
            registry = {n: dict(s) for n, s in self._registry.items()}
        if last is not None and last > 0:
            ds = ds[-last:]
        doc: dict[str, Any] = {
            "decisions": ds, "managed": managed, "registry": registry,
            "endpoints": self._router.endpoints(),
        }
        if self._lease is not None:
            doc["leader"] = {"leading": self._lease.leading,
                             "holder": self._lease.holder,
                             "term": self._lease.term}
        return doc

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Expose :meth:`control_dump` over the wire (the
        ``control_dump`` frame op); returns the service endpoint.
        Stopped by :meth:`close`."""
        if self._service is None:
            self._service = ControlService(self, host, port)
            self._service.start()
        return self._service.endpoint

    def _record(self, d: ControlDecision) -> None:
        with self._lock:
            self._decisions.append(d)
        _log.info("control: %s %s (%s)", d.action,
                  d.endpoint or "", d.reason)

    def _client_for(self, ep: str) -> InferenceClient:
        r = self._router._replica_for(ep)
        if r is None:
            raise ConnectionError(f"{ep} is not a member")
        return self._router._client(r)

    def set_quotas(self, quotas: dict[str, float]) -> dict[str, list[str]]:
        """Push a live tenant-share map to every healthy replica's
        schedulers over the existing control channel (the
        ``sched_quotas`` wire op) — quota shares reconfigure without a
        replica restart (the PR-18 residue). Best-effort per replica:
        unreachable or scheduler-less members are recorded, not fatal.
        Returns ``endpoint -> generator names updated``; the push lands
        in the decision log with the applied map as its evidence."""
        q = {str(k): float(v) for k, v in (quotas or {}).items()}
        applied: dict[str, list[str]] = {}
        errors: list[str] = []
        for m in self._router.members():
            if not m["healthy"] or m["cordoned"]:
                continue
            ep = m["endpoint"]
            try:
                applied[ep] = self._client_for(ep).sched_quotas(q)
            except (ConnectionError, RuntimeError, OSError) as e:
                errors.append(f"{ep}: {type(e).__name__}: {e}")
        stat_add("control/quota_pushes")
        self._record(ControlDecision(
            action="set_quotas", ts=time.time(),
            reason=(f"pushed tenant quotas to {len(applied)} replica(s)"
                    + (f"; failed: {'; '.join(errors)}" if errors else "")),
            clean=not errors,
            signals={"quotas": q,
                     "updated": {ep: list(g) for ep, g in applied.items()}}))
        return applied

    # -- control-plane HA --------------------------------------------------
    @property
    def lease(self) -> LeaderLease | None:
        """The leader lease when HA is on (tests and dashboards read
        leading/term through it); None at the flag default."""
        return self._lease

    def _journal_rec(self, op: str, **fields: Any) -> None:
        """Write-ahead journal a fleet mutation — leaders only (a
        standby writing would interleave with the leader's compaction).
        Appends are fsync'd before the caller acts; a journal failure
        is counted and logged loudly, never silently dropped."""
        if self._journal is None or self._lease is None \
                or not self._lease.leading:
            return
        if not self._lease.is_current():
            # the journal is an actuator too: a deposed leader whose
            # local flag is stale must not interleave records with the
            # successor's compaction
            stat_add("control/ha_fenced")
            _log.warning("control-ha: journal %s fenced (deposed)", op)
            return
        fields.setdefault("term", self._lease.term)
        try:
            self._journal.append(op, **fields)
        except (ConnectionError, RuntimeError, OSError) as e:
            stat_add("control/ha_journal_errors")
            _log.warning("control-ha: journal %s failed: %s", op, e)

    def _ha_fenced(self, action: str, reason: str,
                   signals: dict[str, Any], e: BaseException,
                   endpoint: str | None = None) -> ControlDecision:
        """A spawner action was rejected at the actuator because the
        lease names a newer (holder, term): record the typed decision —
        the deposed leader's intent is explainable, never executed."""
        d = ControlDecision(
            "fenced", endpoint=endpoint, ts=time.time(), signals=signals,
            reason=f"{reason}; {action} rejected by epoch fence: {e}")
        self._record(d)
        return d

    def _probe_alive(self, ep: str) -> dict | None:
        """One never-shed health probe; the doc when the endpoint is
        up, None when it is not (adoption-time liveness check)."""
        try:
            with InferenceClient(ep, timeout=5.0, retries=0) as c:
                doc = c.health(stats=False)
            return doc if doc.get("status") == "ok" else None
        except (ConnectionError, RuntimeError, OSError):
            return None

    def _ha_state(self) -> FleetState:
        """The live fleet state as a journal checkpoint snapshot."""
        st = FleetState()
        with self._lock:
            managed = sorted(self._managed)
            st.registry = {n: dict(s) for n, s in self._registry.items()}
        for ep in managed:
            st.managed[ep] = {"pid": self._spawner.pid_of(ep)}
        st.draining = self._draining
        return st

    def _ha_gate(self) -> ControlDecision | None:
        """Per-tick leadership step: renew when leading (deposed → step
        aside, replicas untouched — the successor adopts them), acquire
        + take over when the lease is free, hold as a standby
        otherwise. None means this controller leads and the reconcile
        pass should run."""
        lease = self._lease
        if lease.leading:
            if lease.renew():
                stat_add("control/ha_renewals")
                return None
            stat_add("control/ha_deposed")
            cur = lease.peek() or {}
            d = ControlDecision(
                "deposed", ts=time.time(),
                reason=f"lease lost to ({cur.get('holder')!r}, term "
                       f"{cur.get('term')}); stepping aside — managed "
                       "replicas left running for the successor to "
                       "adopt")
            self._record(d)
            return d
        if lease.try_acquire():
            stat_add("control/ha_acquired")
            self._ha_takeover()
            return None
        stat_add("control/ha_standby_ticks")
        cur = lease.peek() or {}
        return ControlDecision(
            "hold", ts=time.time(),
            reason=f"standby: lease held by ({cur.get('holder')!r}, "
                   f"term {cur.get('term')})",
            signals={"leading": False, "term": lease.term})

    def _ha_takeover(self) -> None:
        """Newly-elected leader: replay the journal to the previous
        leader's exact fleet, probe every journaled endpoint, adopt the
        live ones (their streams are untouched — routing membership and
        the managed set are restored around them), replace the dead,
        resume any in-progress drain, and bootstrap up to
        ``min_replicas``."""
        stat_add("control/ha_takeovers")
        state = self._journal.replay()
        if state.lost_spawns:
            # spawn intents that never reported an endpoint: the old
            # leader died inside the spawner — unaddressable by replay,
            # surfaced instead of silently forgotten
            stat_add("control/ha_lost_spawns", state.lost_spawns)
            _log.warning("control-ha: %d journaled spawn intent(s) "
                         "never reported an endpoint",
                         state.lost_spawns)
        with self._lock:
            for name, spec in state.registry.items():
                self._registry.setdefault(name, dict(spec))
        try:
            members = set(self._router.endpoints())
            for ep, meta in sorted(state.managed.items()):
                if self._probe_alive(ep) is not None:
                    self._spawner.adopt(ep, pid=meta.get("pid"))
                    if ep not in members:
                        self._router.add_endpoint(ep)
                    with self._lock:
                        self._managed.add(ep)
                    stat_add("control/ha_adopted")
                    self._journal_rec("adopt", ep=ep,
                                      pid=meta.get("pid"))
                    self._record(ControlDecision(
                        "adopt", endpoint=ep, ts=time.time(),
                        reason=f"takeover (term {self._lease.term}): "
                               "journaled replica alive — adopted, "
                               "streams untouched"))
                else:
                    self._journal_rec("remove", ep=ep)
                    self._record(ControlDecision(
                        "replace", endpoint=ep, ts=time.time(),
                        reason=f"takeover (term {self._lease.term}): "
                               "journaled replica dead"))
                    stat_add("control/replaced")
                    self._scale_up("replacing dead replica found at "
                                   "takeover", {})
            if state.draining is not None:
                with self._lock:
                    resumable = state.draining in self._managed
                if resumable:
                    stat_add("control/ha_drains_resumed")
                    self._record(ControlDecision(
                        "drain_resume", endpoint=state.draining,
                        ts=time.time(),
                        reason="takeover: previous leader journaled an "
                               "unfinished sticky drain — resuming"))
                    self.scale_down(
                        victim=state.draining,
                        reason="resuming drain journaled by previous "
                               "leader")
            while len(self._router.endpoints()) < self.min_replicas:
                if self._scale_up("bootstrap to min_replicas",
                                  {}).action != "scale_up":
                    break
            with self._lock:
                self._last_scale = 0.0   # takeover is not a reactive
                #                          scale event: no cooldown
        except StaleEpochError as e:     # deposed mid-takeover: the
            self._ha_fenced("takeover", # newer leader finishes the job
                            f"takeover term {self._lease.term}", {}, e)
            return
        try:     # a takeover is a natural checkpoint: bound the next
            self._journal.compact(self._ha_state())   # leader's replay
        except (ConnectionError, RuntimeError, OSError) as e:
            stat_add("control/ha_journal_errors")
            _log.warning("control-ha: takeover compaction failed: %s", e)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingController":
        """Spawn up to ``min_replicas`` (counting adopted endpoints) and
        start the reconcile loop (``interval_s > 0``). With HA on the
        bootstrap is deferred to leadership: a standby must not spawn —
        the leader bootstraps at takeover."""
        if self._lease is None:
            while len(self._router.endpoints()) < self.min_replicas:
                if self._scale_up("bootstrap to min_replicas",
                                  {}).action != "scale_up":
                    break
            with self._lock:
                self._last_scale = 0.0   # bootstrap is not a reactive
                #                  scale event; it must not arm cooldown
        if self.interval_s > 0 and self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="serving-control")
            self._thread.start()
        return self

    def close(self, stop_replicas: bool = True) -> None:
        """Stop the loop; optionally drain-stop every managed replica
        (adopted endpoints are never touched)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.interval_s * 2, 2.0))
        if self._service is not None:
            self._service.stop()
            self._service = None
        if stop_replicas:
            with self._lock:
                eps = list(self._managed)
                self._managed.clear()
            for ep in eps:
                self._journal_rec("remove", ep=ep)
                try:
                    self._router.remove_endpoint(ep)
                    self._spawner.stop(ep, drain_s=min(self.drain_s, 2.0))
                except (ConnectionError, RuntimeError, OSError):
                    # StaleEpochError lands here too: a deposed
                    # controller's close must not stop the successor's
                    # adopted replicas
                    pass
        if self._lease is not None:
            self._lease.release()
            self._lease.close()
        if self._journal is not None:
            self._journal.close()
        if self._own_router:
            self._router.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _loop(self) -> None:
        while not self._stop.wait(_jittered(self.interval_s)):
            try:
                self.tick()
            except Exception:            # pragma: no cover - never dies
                stat_add("control/tick_errors")

    # -- the reconcile tick ------------------------------------------------
    def tick(self) -> ControlDecision:
        """One reconcile pass: collect fleet health, self-heal dead
        managed replicas, enforce the warm tier, and make (at most) one
        scale decision. Returns the decision (action ``"hold"`` when
        nothing fired); everything except holds also lands in
        :meth:`decisions`."""
        with self._lock:
            if self._closed:
                return ControlDecision("hold", "controller closed",
                                       ts=time.time())
        if self._lease is not None:
            # leadership first: standbys (and a just-deposed leader)
            # return here without touching the fleet
            gate = self._ha_gate()
            if gate is not None:
                return gate
            if self._journal.should_compact():
                try:
                    self._journal.compact(self._ha_state())
                except (ConnectionError, RuntimeError, OSError) as e:
                    stat_add("control/ha_journal_errors")
                    _log.warning("control-ha: compaction failed: %s", e)
        with self._lock, _trace.span("control/tick"):
            stat_add("control/ticks")
            healths = self._router.health(stats_prefix="gen/",
                                          histograms=True)
            self._heal(healths)
            if self.warm_models > 0:
                self._evict_over_capacity(healths)
            signals = self._signals(healths)
            stat_set("control/replicas", signals["replicas"])
            return self._decide(signals)

    def _heal(self, healths: dict[str, dict]) -> None:
        """Replace managed replicas that stay unreachable: remove from
        routing, best-effort stop, spawn a substitute. ``breach_ticks``
        consecutive failed probes gate it — one dropped probe is not a
        death certificate."""
        with self._lock:
            managed = set(self._managed)
        for ep in managed:
            doc = healths.get(ep)
            dead = doc is None or doc.get("status") == "unreachable"
            n = self._unreachable.get(ep, 0) + 1 if dead else 0
            self._unreachable[ep] = n
            if n < max(self.breach_ticks, 1):
                continue
            self._unreachable.pop(ep, None)
            self._record(ControlDecision(
                action="replace", endpoint=ep, ts=time.time(),
                reason=f"unreachable for {n} consecutive ticks: "
                       f"{(doc or {}).get('error', 'no probe')}"))
            stat_add("control/replaced")
            self._journal_rec("remove", ep=ep)
            self._router.remove_endpoint(ep)
            with self._lock:
                self._managed.discard(ep)
            try:
                self._spawner.stop(ep, drain_s=0.0)
            except StaleEpochError as e:
                self._ha_fenced("stop", "replacing dead replica", {},
                                e, endpoint=ep)
                return               # deposed: successor heals the rest
            except (ConnectionError, RuntimeError, OSError):
                pass
            self._scale_up("replacing dead replica", {})

    def _signals(self, healths: dict[str, dict]) -> dict[str, Any]:
        """Fold per-replica health into the fleet signal snapshot the
        scale decision reads (cordoned members are draining capacity —
        excluded).  The scrape also feeds the :class:`MetricsHub`, and
        every latency signal — the windowed TTFT p99 and both burn
        rates — is read back out of the hub's windows, so a decision's
        recorded evidence IS the hub's answer at that tick."""
        cordoned = {m["endpoint"] for m in self._router.members()
                    if m["cordoned"]}
        live = {ep: doc for ep, doc in healths.items()
                if isinstance(doc, dict) and doc.get("status") == "ok"
                and ep not in cordoned}
        n = len(live)
        inflight = sum(int(d.get("inflight", 0)) for d in live.values())
        slots = active = queued = 0
        for d in live.values():
            for g in (d.get("generators") or {}).values():
                slots += int(g.get("slots", 0))
                active += int(g.get("active", 0))
                queued += int(g.get("queued", 0))
        self._hub.ingest(healths)
        win = self._hub.window_histogram("gen/ttft_s",
                                         self.burn_fast_ticks)
        ttft_p99 = float(win["p99"]) if win else None
        if self.target_ttft_s > 0 and self.slo_budget > 0:
            burn_fast, burn_slow = self._hub.burn_rates(
                "gen/ttft_s", self.target_ttft_s, self.slo_budget)
        else:
            burn_fast = burn_slow = 0.0
        out = {
            "replicas": n,
            "managed": len(self._managed),
            "members": len(healths),
            "inflight_mean": inflight / n if n else 0.0,
            "slots": slots, "active": active, "queued": queued,
            "occupancy": active / slots if slots else 0.0,
            "queue_per_replica": queued / n if n else 0.0,
            "ttft_p99_s": ttft_p99,
            "ttft_burn_fast": burn_fast,
            "ttft_burn_slow": burn_slow,
        }
        if self._lease is not None:
            # leadership travels with every decision's evidence: who
            # made this call, under which term
            out["leader"] = {"leading": self._lease.leading,
                             "holder": self._lease.holder,
                             "term": self._lease.term}
        kv = self._hub.fleet_kv()
        if kv is not None:
            # disaggregated-serving visibility: the fleet KV hit rate and
            # tier mix travel with every decision's evidence, so a scale
            # event can be read against how much prefill the store was
            # absorbing at that tick
            out["kv"] = {
                "engines": kv["engines"], "roles": kv["roles"],
                "hit_rate": kv["hit_rate"],
                "fetch_bytes": kv["fetch_bytes"],
                "demotions": kv["demotions"],
                "prefill_recomputed": kv["prefill_recomputed"],
                # tier failure domains: a degraded store or rising
                # fetch_degraded explains a throughput dip as recompute
                # debt, not capacity shortfall — scale decisions read
                # this before adding replicas
                "degraded_engines": kv["degraded_engines"],
                "fetch_degraded": kv["fetch_degraded"],
                "timeouts": kv["timeouts"],
                "breaker_opens": kv["breaker_opens"],
            }
        emb = self._hub.fleet_emb()
        if emb is not None:
            # sparse-serving visibility (FLAGS_serving_emb): the fleet
            # hot-row hit rate, PS pull volume, and per-table version
            # spread travel with every decision's evidence — stale
            # serves or a version spread wider than one explain a tail
            # regression as PS trouble / a propagating rollover, not
            # capacity shortfall
            out["emb"] = {
                "replicas": emb["replicas"],
                "hit_rate": emb["hit_rate"],
                "pulled_rows": emb["pulled_rows"],
                "pulled_bytes": emb["pulled_bytes"],
                "stale_serves": emb["stale_serves"],
                "rollovers": emb["rollovers"],
                "versions": emb["versions"],
            }
        return out

    def _pressure(self, s: dict[str, Any]) -> list[str]:
        """Scale-up pressure reasons (empty = none). Each enabled signal
        contributes independently; the decision log keeps the winning
        reasons verbatim."""
        out = []
        if (self.queue_high > 0
                and s["queue_per_replica"] >= self.queue_high):
            out.append(f"queued generations "
                       f"{s['queue_per_replica']:.2f}/replica >= "
                       f"{self.queue_high:g}")
        if s["slots"] and s["occupancy"] >= self.occupancy_high:
            out.append(f"slot occupancy {s['occupancy']:.2f} >= "
                       f"{self.occupancy_high:g}")
        if (self.target_ttft_s > 0 and self.slo_budget > 0
                and s["ttft_burn_fast"] > self.burn_threshold
                and s["ttft_burn_slow"] > self.burn_threshold):
            # multi-window burn-rate page: the acute window proves it is
            # happening NOW, the sustained window proves it is not a
            # one-tick blip — both must burn budget past the threshold
            p99 = s.get("ttft_p99_s")
            out.append(f"TTFT burn rate fast {s['ttft_burn_fast']:.1f}x"
                       f"/slow {s['ttft_burn_slow']:.1f}x > "
                       f"{self.burn_threshold:g}x of SLO budget "
                       f"{self.slo_budget:g} (p99 "
                       f"{p99 if p99 is None else round(p99, 3)}s vs "
                       f"target {self.target_ttft_s:g}s)")
        if (self.inflight_high > 0
                and s["inflight_mean"] >= self.inflight_high):
            out.append(f"inflight {s['inflight_mean']:.2f}/replica >= "
                       f"{self.inflight_high:g}")
        return out

    def _is_idle(self, s: dict[str, Any]) -> bool:
        if self._pressure(s):
            return False
        if s["queued"] > 0:
            return False
        if s["slots"] and s["occupancy"] > self.occupancy_low:
            return False
        if (self.inflight_high > 0 and s["inflight_mean"]
                > self.inflight_high * self.occupancy_low):
            return False
        return True

    def _decide(self, signals: dict[str, Any]) -> ControlDecision:
        now = time.monotonic()
        pressure = self._pressure(signals)
        if pressure:
            self._breach += 1
            self._idle = 0
        elif self._is_idle(signals):
            self._idle += 1
            self._breach = 0
        else:
            self._breach = 0
            self._idle = 0
        signals = dict(signals, breach_ticks=self._breach,
                       idle_ticks=self._idle)
        if self.max_replicas <= 0:       # autoscaling off (flag default)
            return ControlDecision("hold", "autoscaling disabled "
                                   "(control_max_replicas=0)",
                                   ts=time.time(), signals=signals)
        cooling = (self._last_scale
                   and now - self._last_scale < self.cooldown_s)
        if pressure and self._breach >= self.breach_ticks:
            reason = "; ".join(pressure)
            if cooling:
                d = ControlDecision("hold", f"cooldown holds scale-up "
                                    f"({reason})", ts=time.time(),
                                    signals=signals)
                self._record(d)
                return d
            if signals["replicas"] >= self.max_replicas:
                return ControlDecision(
                    "hold", f"at max_replicas={self.max_replicas} "
                    f"({reason})", ts=time.time(), signals=signals)
            self._breach = 0
            return self._scale_up(reason, signals)
        if self._idle >= self.idle_ticks and not cooling:
            with self._lock:
                candidates = list(self._managed)
            if signals["replicas"] > self.min_replicas and candidates:
                self._idle = 0
                return self.scale_down(
                    reason=f"idle {signals['idle_ticks']} ticks "
                    f"(occupancy {signals['occupancy']:.2f} <= "
                    f"{self.occupancy_low:g}, queue 0)",
                    signals=signals)
        return ControlDecision("hold", "no sustained pressure or idle",
                               ts=time.time(), signals=signals)

    # -- scale events ------------------------------------------------------
    def _spawn_model_set(self) -> list[tuple[str, str]]:
        """Models a fresh replica starts with: every warm-pinned one,
        then registry order up to the warm-tier cap (all of them when
        multiplexing is off)."""
        with self._lock:
            warm = [(n, s["path"]) for n, s in self._registry.items()
                    if s["warm"]]
            cold = [(n, s["path"]) for n, s in self._registry.items()
                    if not s["warm"]]
        if self.warm_models <= 0:
            return warm + cold
        return (warm + cold)[:max(self.warm_models, len(warm))]

    def _spawn_failed(self, reason: str, signals: dict[str, Any],
                      e: BaseException) -> ControlDecision:
        """Count a spawner failure toward the circuit breaker: past
        ``control_spawn_breaker`` consecutive failures the breaker
        opens for ``control_spawn_backoff_s * 2^(extra failures)``
        (capped at 32x) — a poisoned artifact degrades the fleet
        instead of hot-looping crash spawns. The next attempt after the
        backoff elapses is the half-open trial; success closes the
        breaker."""
        stat_add("control/spawn_failures")
        suffix = ""
        with self._lock:
            self._spawn_fails += 1
            if 0 < self.spawn_breaker <= self._spawn_fails:
                backoff = self.spawn_backoff_s * min(
                    2 ** (self._spawn_fails - self.spawn_breaker), 32)
                self._spawn_open_until = time.monotonic() + backoff
                stat_add("control/spawn_breaker_opened")
                suffix = (f"; circuit breaker OPEN for {backoff:g}s "
                          f"({self._spawn_fails} consecutive failures "
                          f">= control_spawn_breaker="
                          f"{self.spawn_breaker})")
        d = ControlDecision(
            "spawn_failed", ts=time.time(), signals=signals,
            reason=f"{reason}; spawn raised "
                   f"{type(e).__name__}: {e}{suffix}")
        self._record(d)
        return d

    def _scale_up(self, reason: str,
                  signals: dict[str, Any]) -> ControlDecision:
        with _trace.span("control/scale_up"):
            with self._lock:
                remaining = self._spawn_open_until - time.monotonic()
            if self.spawn_breaker > 0 and remaining > 0:
                stat_add("control/spawn_skipped")
                d = ControlDecision(
                    "spawn_breaker", ts=time.time(), signals=signals,
                    reason=f"{reason}; spawn circuit breaker open for "
                           f"{remaining:.1f}s more after "
                           f"{self._spawn_fails} consecutive spawn "
                           "failures — not calling the spawner")
                self._record(d)
                return d
            try:
                _fault.inject("control.spawn")
                # WAL: the intent is durable before the spawner acts —
                # a leader dying inside spawn() leaves a journaled
                # intent its successor surfaces as a lost spawn
                self._journal_rec("spawn_intent")
                ep = self._spawner.spawn()
            except StaleEpochError as e:
                return self._ha_fenced("spawn", reason, signals, e)
            except Exception as e:
                return self._spawn_failed(reason, signals, e)
            self._journal_rec("spawn", ep=ep,
                              pid=self._spawner.pid_of(ep))
            with self._lock:         # half-open trial succeeded (or the
                self._spawn_fails = 0     # breaker was never tripped):
                self._spawn_open_until = 0.0   # close the breaker
            try:                 # registry models before traffic arrives
                with InferenceClient(ep, retries=1) as c:
                    for name, path in self._spawn_model_set():
                        c.load_model(name, path)
            except (ConnectionError, RuntimeError, OSError) as e:
                _log.warning("control: model preload on %s failed: %s",
                             ep, e)
            self._router.add_endpoint(ep)
            with self._lock:
                self._managed.add(ep)
                self._last_scale = time.monotonic()
            stat_add("control/scale_ups")
            d = ControlDecision("scale_up", endpoint=ep, reason=reason,
                                ts=time.time(), signals=signals)
            self._record(d)
            return d

    def _pick_victim(self) -> str | None:
        """Least-loaded managed, non-cordoned replica (in-flight + active
        generations from a fresh health probe; unreachable counts as
        already-empty)."""
        with self._lock:
            managed = set(self._managed)
        cordoned = {m["endpoint"] for m in self._router.members()
                    if m["cordoned"]}
        best, best_load = None, None
        for ep in sorted(managed - cordoned):
            try:
                doc = self._client_for(ep).health(stats=False)
                load = int(doc.get("inflight", 0)) + sum(
                    int(g.get("active", 0)) + int(g.get("queued", 0))
                    for g in (doc.get("generators") or {}).values())
            except (ConnectionError, RuntimeError, OSError):
                load = 0
            if best_load is None or load < best_load:
                best, best_load = ep, load
        return best

    def scale_down(self, victim: str | None = None, *,
                   reason: str = "manual",
                   signals: dict[str, Any] | None = None,
                   drain_s: float | None = None) -> ControlDecision:
        """Sticky-drain one replica out of the fleet: cordon (new picks
        stop; in-flight streams keep their replica), wait for its work —
        including every undelivered generation — to finish, then stop
        and remove it. Returns the decision; ``clean=False`` means the
        drain deadline forced the stop (``control/drain_forced``)."""
        victim = victim or self._pick_victim()
        if victim is None:
            d = ControlDecision("hold", f"{reason}; no managed replica "
                                "to scale down", ts=time.time(),
                                signals=signals or {})
            self._record(d)
            return d
        deadline = self.drain_s if drain_s is None else float(drain_s)
        with _trace.span("control/drain", endpoint=victim):
            t0 = time.monotonic()
            # WAL: the drain is durable before the cordon — a leader
            # dying mid-drain leaves its successor a journaled victim
            # to resume waiting on (inflight==0 && undelivered==0)
            self._journal_rec("drain_begin", ep=victim)
            self._draining = victim
            self._router.cordon(victim)
            clean = self._await_drained(victim, deadline)
            took = time.monotonic() - t0
            observe("control/drain_s", took)
            if not clean:
                stat_add("control/drain_forced")
            self._journal_rec("remove", ep=victim)
            try:
                self._spawner.stop(victim,
                                   drain_s=max(deadline - took, 0.5))
            except StaleEpochError as e:
                self._draining = None
                return self._ha_fenced("stop", reason, signals or {},
                                       e, endpoint=victim)
            except (ConnectionError, RuntimeError, OSError) as e:
                _log.warning("control: stop of %s failed: %s", victim, e)
            self._journal_rec("drain_end", ep=victim, clean=clean)
            self._draining = None
            self._router.remove_endpoint(victim)
            with self._lock:
                self._managed.discard(victim)
                self._last_scale = time.monotonic()
            stat_add("control/scale_downs")
            d = ControlDecision(
                "scale_down", endpoint=victim, clean=clean,
                ts=time.time(), signals=signals or {},
                reason=f"{reason}; drained in {took:.2f}s"
                       + ("" if clean else
                          f" (FORCED at deadline {deadline:g}s)"))
            self._record(d)
            return d

    def _await_drained(self, ep: str, deadline: float) -> bool:
        """True once the cordoned replica is provably empty: zero
        in-flight wire requests AND zero undelivered generations
        (running, queued, or finished-but-final-poll-unanswered), seen
        twice in a row — a streaming client between polls must not be
        mistaken for done."""
        end = time.monotonic() + max(deadline, 0.0)
        consecutive = 0
        while time.monotonic() < end:
            try:
                doc = self._client_for(ep).health(stats=False)
            except (ConnectionError, RuntimeError, OSError):
                return True              # already gone
            busy = int(doc.get("inflight", 0)) + sum(
                int(g.get("undelivered", g.get("active", 0)))
                for g in (doc.get("generators") or {}).values())
            if busy == 0:
                consecutive += 1
                if consecutive >= 2:
                    return True
            else:
                consecutive = 0
            time.sleep(0.05)
        return False

    def scale_to(self, n: int, reason: str = "manual") -> None:
        """Operator override to an absolute managed-fleet size — same
        spawn/sticky-drain paths as the automatic decisions, no
        hysteresis or cooldown."""
        n = max(int(n), 0)
        while len(self._router.endpoints()) < n:
            if self._scale_up(reason, {}).action != "scale_up":
                break
        while len(self._router.endpoints()) > n:
            if self.scale_down(reason=reason).action != "scale_down":
                break
