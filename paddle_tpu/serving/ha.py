"""Control-plane high availability: leased leadership, a durable
fleet-state journal, and split-brain fencing for the serving
controller.

Reference role: the coordinator-liveness half of the reference fleet
stack — heartbeat + barrier + elastic master restart kept the trainer
coordinator from being a silent single point of failure. paddle_tpu's
:class:`~paddle_tpu.serving.control.ServingController` had the same
hole: one in-process object whose whole fleet state (managed set,
model registry, decision ring, in-progress drains) died with it while
orphaned ``replica_main`` subprocesses served forever. This module is
the remedy, layered on substrates the repo already ships:

- :class:`LeaderLease` — a file-based lease on a shared directory or a
  ``ptfs://`` WireFS root (the same substrate the KV store spills to).
  N controllers run; the one holding the lease acts, the rest tick as
  standbys and claim the lease — with a bumped **term** — once it goes
  a TTL without renewal. Acquisition is write-then-read-back over an
  atomic rename, which resolves most races; the residual window where
  two claimants briefly both believe (file leases have no CAS) is
  closed at the *actuator* by :class:`FencedSpawner`, not here — the
  lease provides liveness, fencing provides safety.
- :class:`FleetJournal` — an append-only JSON-lines journal of every
  fleet-mutating action (``spawn``/``adopt``/``remove``/
  ``register_model``/``drain_begin``/``drain_end``), fsync'd before
  the action it records, compacted into a checkpoint snapshot once it
  grows past ``FLAGS_control_ha_compact_records``. :meth:`replay`
  folds checkpoint + journal (tolerating a torn final line — the
  previous leader died mid-append) back into the exact managed set,
  registry, and any drain in progress.
- :class:`FencedSpawner` — wraps a ``ReplicaSpawner`` so every
  spawn/stop/kill/adopt first validates the caller's (holder, term)
  against the lease file and raises the typed :class:`StaleEpochError`
  when a newer leader holds it: a deposed leader's queued actions are
  rejected at the actuator (no double-spawn, no stop-by-zombie).
- :class:`ControlService` — a tiny frame service exposing the
  controller's :class:`~paddle_tpu.serving.control.ControlDecision`
  ring (plus leader/term and the managed set) over the wire as a
  ``control_dump`` op, so ``tools/obs_dump.py`` can report WHY the
  fleet scaled even across a takeover; :func:`control_dump` is the
  client half.

Everything here is constructed only when ``FLAGS_control_ha_lease_dir``
is non-empty; the flag-default controller never imports a lease, never
writes a journal byte, and spawns no extra thread.
"""

from __future__ import annotations

import json
import os
import socket as _socket
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from paddle_tpu.core.flags import flag
from paddle_tpu.core.logging import get_logger
from paddle_tpu.core.monitor import stat_add
from paddle_tpu.core.wire import FrameClient, FrameService, send_frame
from paddle_tpu.io.fs import CHUNK_BYTES, fs_for_path, is_remote_path

__all__ = ["LeaderLease", "FleetJournal", "FleetState", "FencedSpawner",
           "StaleEpochError", "ControlService", "control_dump",
           "CONTROL_OPS"]

_log = get_logger()

LEASE_FILE = "lease.json"
JOURNAL_FILE = "journal.jsonl"
STATE_FILE = "state.json"


class StaleEpochError(RuntimeError):
    """A fleet actuation carried a (holder, term) the lease no longer
    names — the caller was deposed; the action must not execute."""


class _Store:
    """Byte-level lease/journal IO over the HA root: a local shared
    directory (fsync'd writes, atomic rename replace) or a ``ptfs://``
    WireFS endpoint (durability is the storage node's write+close; the
    atomic replace is the server-side rename)."""

    def __init__(self, root: str):
        self.root = str(root).rstrip("/")
        self._remote = is_remote_path(self.root)
        self._fs = fs_for_path(self.root) if self._remote else None
        if self._remote:
            self._fs.mkdirs(self.root)
        else:
            os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        if self._remote:
            return f"{self.root}/{name}"
        return os.path.join(self.root, name)

    def read(self, name: str) -> bytes | None:
        p = self._path(name)
        if self._remote:
            try:
                out, offset = b"", 0
                while True:
                    h, data = self._fs._client._request(
                        "read", {"path": self._fs._rel(p),
                                 "offset": offset, "length": CHUNK_BYTES})
                    out += data
                    offset += len(data)
                    if h.get("eof", True):
                        return out
            except (ConnectionError, RuntimeError, OSError):
                return None
        try:
            with open(p, "rb") as f:
                return f.read()
        except OSError:
            return None

    def append(self, name: str, data: bytes) -> None:
        p = self._path(name)
        if self._remote:
            # appends are fail-fast non-idempotent on the wire (a
            # replayed append would double a record — io/fs.py posture)
            self._fs._client._request(
                "write", {"path": self._fs._rel(p), "nbytes": len(data),
                          "append": True}, data, idempotent=False)
            return
        with open(p, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def replace(self, name: str, data: bytes) -> None:
        """Atomic whole-file replace: write a unique temp, rename over
        the target (readers see the old or the new bytes, never a
        tear)."""
        tmp = f"{name}.{uuid.uuid4().hex[:8]}.tmp"
        tp, p = self._path(tmp), self._path(name)
        if self._remote:
            self._fs._client._request(
                "write", {"path": self._fs._rel(tp), "nbytes": len(data),
                          "append": False}, data, idempotent=True)
            self._fs.mv(tp, p)
            return
        with open(tp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tp, p)
        try:                      # rename durability: fsync the dir
            dfd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:           # pragma: no cover - platform quirk
            pass

    def delete(self, name: str) -> None:
        p = self._path(name)
        try:
            if self._remote:
                self._fs.delete(p)
            else:
                os.remove(p)
        except (ConnectionError, RuntimeError, OSError):
            pass

    def close(self) -> None:
        if self._fs is not None:
            self._fs.close()


# ---------------------------------------------------------------------------
# leader lease
# ---------------------------------------------------------------------------

class LeaderLease:
    """File-based leader lease with TTL and monotonically-bumped terms.

    One probe per call, no background thread: the controller's tick IS
    the heartbeat. ``try_acquire`` claims an absent/expired lease with
    ``term = observed + 1`` and confirms by read-back; ``renew``
    refreshes the deadline under the same term and reports ``False``
    (deposed) the instant the file names someone else. Timestamps are
    wall-clock (`time.time`) because they must compare across hosts —
    the TTL is assumed to dwarf clock skew, same as every file-lease
    scheme. The unavoidable acquire race of a CAS-free file is fenced
    downstream by :class:`FencedSpawner`/:meth:`is_current`.
    """

    def __init__(self, root: str, *, ttl_s: float | None = None,
                 holder: str | None = None):
        self._store = _Store(root)
        self.ttl_s = float(flag("control_ha_lease_ttl_s")
                           if ttl_s is None else ttl_s)
        if holder is None:
            holder = str(flag("control_ha_holder") or "")
        self.holder = holder or (f"{_socket.gethostname()}:{os.getpid()}:"
                                 f"{uuid.uuid4().hex[:6]}")
        self.term = 0
        self.leading = False

    def peek(self) -> dict[str, Any] | None:
        """The current lease document, or None (absent/unparseable —
        a torn write reads as no lease and is simply re-claimed)."""
        raw = self._store.read(LEASE_FILE)
        if not raw:
            return None
        try:
            doc = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None
        return doc if isinstance(doc, dict) else None

    def _write(self, term: int) -> bool:
        now = time.time()
        self._store.replace(LEASE_FILE, json.dumps(
            {"holder": self.holder, "term": int(term),
             "expires": now + self.ttl_s, "ts": now}).encode())
        back = self.peek()
        return (back is not None and back.get("holder") == self.holder
                and int(back.get("term", -1)) == int(term))

    def try_acquire(self) -> bool:
        """Single acquisition probe. True only when this holder now
        leads (fresh claim or an expired lease taken over at
        ``term + 1``)."""
        cur = self.peek()
        now = time.time()
        if (cur is not None and cur.get("holder") != self.holder
                and now < float(cur.get("expires", 0.0))):
            return False                     # live foreign lease
        term = int(cur.get("term", 0)) + 1 if cur else 1
        if self._write(term):
            self.term = term
            self.leading = True
            return True
        self.leading = False
        return False

    def renew(self) -> bool:
        """Refresh the deadline under the current term. False — and no
        write — once the file names another (holder, term): the caller
        is deposed and must stop acting."""
        if not self.leading:
            return False
        cur = self.peek()
        if (cur is None or cur.get("holder") != self.holder
                or int(cur.get("term", -1)) != self.term):
            self.leading = False
            return False
        if self._write(self.term):
            return True
        self.leading = False
        return False

    def is_current(self) -> bool:
        """Actuator-side fence: does the lease file, right now, name
        this (holder, term)? Expiry is NOT checked — an expired lease
        still naming us means nobody took over yet, and acting is safe;
        the moment a successor claims, the file names them and this
        goes False."""
        cur = self.peek()
        return (cur is not None and cur.get("holder") == self.holder
                and int(cur.get("term", -1)) == self.term)

    def release(self) -> None:
        """Drop the lease iff it is still ours (a standby's release
        must never delete the leader's lease)."""
        if self.leading and self.is_current():
            self._store.delete(LEASE_FILE)
        self.leading = False

    def close(self) -> None:
        self._store.close()


# ---------------------------------------------------------------------------
# durable fleet-state journal
# ---------------------------------------------------------------------------

@dataclass
class FleetState:
    """What a journal replay reconstructs: the managed set (with the
    pids needed to stop adopted subprocess replicas), the model
    registry, any drain the previous leader left in progress, and the
    count of spawn intents that never reported an endpoint (the
    half-spawned orphans replay cannot address)."""

    managed: dict[str, dict[str, Any]] = field(default_factory=dict)
    registry: dict[str, dict[str, Any]] = field(default_factory=dict)
    draining: str | None = None
    lost_spawns: int = 0

    def apply(self, rec: dict[str, Any]) -> None:
        op = rec.get("op")
        if op == "spawn_intent":
            self.lost_spawns += 1
        elif op == "spawn":
            self.lost_spawns = max(self.lost_spawns - 1, 0)
            self.managed[rec["ep"]] = {"pid": rec.get("pid")}
        elif op == "adopt":
            self.managed[rec["ep"]] = {"pid": rec.get("pid")}
        elif op == "remove":
            self.managed.pop(rec.get("ep"), None)
            if self.draining == rec.get("ep"):
                self.draining = None
        elif op == "register_model":
            self.registry[rec["name"]] = {"path": rec.get("path"),
                                          "warm": bool(rec.get("warm"))}
        elif op == "drain_begin":
            self.draining = rec.get("ep")
        elif op == "drain_end":
            if self.draining == rec.get("ep"):
                self.draining = None
        # unknown ops: skipped (a newer leader's journal stays readable)

    def as_dict(self) -> dict[str, Any]:
        return {"managed": {ep: dict(m) for ep, m in self.managed.items()},
                "registry": {n: dict(s) for n, s in self.registry.items()},
                "draining": self.draining,
                "lost_spawns": int(self.lost_spawns)}

    @classmethod
    def from_dict(cls, doc: dict[str, Any]) -> "FleetState":
        return cls(managed={ep: dict(m) for ep, m in
                            (doc.get("managed") or {}).items()},
                   registry={n: dict(s) for n, s in
                             (doc.get("registry") or {}).items()},
                   draining=doc.get("draining"),
                   lost_spawns=int(doc.get("lost_spawns", 0)))


class FleetJournal:
    """Append-only JSON-lines journal + compacted checkpoint snapshot.

    Write-ahead discipline: the caller appends (fsync'd) BEFORE the
    action the record describes, so a replayed journal is always a
    superset of what actually happened — a crash between append and
    action costs a probe at takeover (the endpoint is probed dead or
    alive either way), never a forgotten replica. :meth:`compact`
    atomically snapshots a full :class:`FleetState` and truncates the
    journal, bounding replay cost.
    """

    def __init__(self, root: str, *, compact_records: int | None = None):
        self._store = _Store(root)
        self.compact_records = int(flag("control_ha_compact_records")
                                   if compact_records is None
                                   else compact_records)
        self.pending = 0           # records since the last compaction

    def append(self, op: str, **fields: Any) -> None:
        rec = {"op": op, "ts": time.time(), **fields}
        self._store.append(JOURNAL_FILE,
                           (json.dumps(rec) + "\n").encode())
        self.pending += 1
        stat_add("control/ha_journal_records")

    def replay(self) -> FleetState:
        state = FleetState()
        ckpt = self._store.read(STATE_FILE)
        if ckpt:
            try:
                state = FleetState.from_dict(json.loads(ckpt.decode()))
            except (ValueError, UnicodeDecodeError):
                _log.warning("control-ha: unreadable state checkpoint; "
                             "replaying journal from scratch")
        n = 0
        raw = self._store.read(JOURNAL_FILE) or b""
        for line in raw.decode(errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # torn tail: the writer died mid-append; every record
                # before it is intact, nothing after it exists
                break
            if isinstance(rec, dict):
                state.apply(rec)
                n += 1
        self.pending = n
        return state

    def should_compact(self) -> bool:
        return 0 < self.compact_records <= self.pending

    def compact(self, state: FleetState) -> None:
        """Checkpoint ``state`` (atomic replace) then truncate the
        journal. Snapshot first: a crash between the two replays the
        checkpoint plus a journal whose records are all already folded
        into it — every journal op is idempotent under re-apply."""
        self._store.replace(STATE_FILE,
                            json.dumps(state.as_dict()).encode())
        self._store.replace(JOURNAL_FILE, b"")
        self.pending = 0
        stat_add("control/ha_compactions")

    def close(self) -> None:
        self._store.close()


# ---------------------------------------------------------------------------
# epoch fencing at the actuator
# ---------------------------------------------------------------------------

class FencedSpawner:
    """Wraps a ``ReplicaSpawner`` so every action is fenced on the
    caller's (holder, term): a deposed leader's queued spawn/stop/kill
    raises the typed :class:`StaleEpochError` instead of executing —
    the split-brain window a CAS-free file lease cannot close is closed
    here, where the fleet is actually mutated."""

    def __init__(self, inner, lease: LeaderLease):
        self.inner = inner
        self._lease = lease

    def _fence(self, action: str, endpoint: str | None = None) -> None:
        if self._lease.is_current():
            return
        cur = self._lease.peek() or {}
        stat_add("control/ha_fenced")
        raise StaleEpochError(
            f"{action}{' ' + endpoint if endpoint else ''} fenced: this "
            f"controller holds ({self._lease.holder!r}, term "
            f"{self._lease.term}) but the lease names "
            f"({cur.get('holder')!r}, term {cur.get('term')})")

    def spawn(self) -> str:
        self._fence("spawn")
        return self.inner.spawn()

    def stop(self, endpoint: str, drain_s: float = 0.0) -> None:
        self._fence("stop", endpoint)
        self.inner.stop(endpoint, drain_s=drain_s)

    def kill(self, endpoint: str) -> None:
        self._fence("kill", endpoint)
        self.inner.kill(endpoint)

    def adopt(self, endpoint: str, pid: int | None = None) -> None:
        self._fence("adopt", endpoint)
        self.inner.adopt(endpoint, pid=pid)

    def pid_of(self, endpoint: str) -> int | None:
        return self.inner.pid_of(endpoint)


# ---------------------------------------------------------------------------
# the decision ring over the wire
# ---------------------------------------------------------------------------

CONTROL_OPS = {"control_dump": 1}


class ControlService(FrameService):
    """Frame service exposing a controller's decision ring, managed
    set, registry, and leader/term over the wire (``control_dump``).
    Decisions used to die with the controller process; scraped over
    this op they survive it — ``tools/obs_dump.py --control`` reports
    why the fleet scaled across a takeover."""

    op_names = {v: k for k, v in CONTROL_OPS.items()}

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(host, port)
        self._controller = controller

    def _dispatch(self, sock, op, header, payload) -> bool:
        try:
            if op == CONTROL_OPS["control_dump"]:
                last = header.get("last")
                send_frame(sock, 0, self._controller.control_dump(
                    last=int(last) if last else None))
            else:
                send_frame(sock, 1, {"error": f"unknown op {op}"})
        except Exception as e:  # surfaced client-side as RuntimeError
            send_frame(sock, 1, {"error": f"{type(e).__name__}: {e}"})
        return True


def control_dump(endpoint: str, *, last: int | None = None,
                 timeout: float | None = None) -> dict[str, Any]:
    """Scrape a :class:`ControlService`: the decision ring (optionally
    only the last N), managed set, registry, and leader block."""
    client = FrameClient(endpoint, CONTROL_OPS, service="control",
                         timeout=timeout, idempotent=("control_dump",))
    try:
        header = {} if last is None else {"last": int(last)}
        doc, _ = client._request("control_dump", header)
        return doc
    finally:
        client.close()
