"""Device layout for the serving engine: where engine state lives.

The :class:`~paddle_tpu.serving.engine.GenerationEngine` owns a pile of
device state (the batched KV cache or page pool, per-slot token/
position/key/sampling arrays) and a set of compiled entry points
(bucketed prefill, fused decode, speculative verify, draft lookahead)
that thread that state through ``donate_argnums=(0,)``. This module
puts ALL of that behind one object so the engine itself never touches
``jax.sharding``:

* ``DeviceLayout(0)`` — the default, from ``FLAGS_gen_mesh_tp=0`` — is
  the **identity layout**: no mesh is built, ``place_state`` returns
  its argument, and ``jit_entry`` is a plain ``jax.jit`` — the compiled
  surface is byte-identical to the pre-sharding build.
* ``DeviceLayout(tp)`` for ``tp >= 1`` builds a tensor-parallel mesh
  over the first ``tp`` local devices (``parallel.mesh.serving_mesh``),
  places model parameters with the per-module spec map (Megatron
  column/row split — ``models/llama.py``'s table), shards the KV
  cache/page pool on the KV-head axis (``models/generation.py``'s
  ``STACKED_KV_SPEC``/``POOL_KV_SPEC``), and gives every compiled entry
  point explicit in/out shardings so XLA's SPMD partitioner inserts the
  collectives. Page tables and the scheduler stay host-side and
  replicated — sharding is invisible above the compiled boundary.

A mesh-backed engine is ONE logical replica: one endpoint, one health
doc. The router/controller need no changes beyond reading the
``device`` stats block (:meth:`DeviceLayout.describe`), which carries
platform, device count, mesh axis sizes, and per-device KV bytes.

Byte-identity across layouts is a hard contract, not an aspiration:
matmul column/row splits concatenate/psum exact partial results, the
KV-head split never splits a reduction, and sampling runs on the
replicated logits — so greedy AND sampled token streams match the
unsharded engine bit-for-bit, and stream failover (``rng_skip``) can
resume a stream started on a tp=4 replica on an unsharded survivor.
Verified on CPU via ``XLA_FLAGS=--xla_force_host_platform_device_count``
in ``tests/test_sharded_gen.py`` (``pytest -m sharded``).
"""

from __future__ import annotations

from typing import Any

__all__ = ["DeviceLayout"]


class DeviceLayout:
    """Mesh-or-identity placement policy for engine device state.

    ``tp=0`` (the hard-off default): ``mesh is None`` and every method
    is a passthrough. ``tp>=1``: a ``serving_mesh(tp)`` over the first
    ``tp`` local devices; ``tp=1`` exercises the full sharded code path
    (explicit shardings, NamedSharding state) on a one-device mesh —
    useful for shaking out layout bugs without multi-device hardware.
    """

    def __init__(self, tp: int = 0, devices: Any = None):
        self.tp = int(tp)
        if self.tp <= 0:
            self.mesh = None
        else:
            from paddle_tpu.parallel.mesh import serving_mesh
            self.mesh = serving_mesh(self.tp, devices)

    @property
    def sharded(self) -> bool:
        return self.mesh is not None

    # -- placement ---------------------------------------------------------
    def shard_model(self, model):
        """Sharded params via the model's own ``shard_for_inference``
        (which validates head divisibility) when it has one, else the
        generic per-module spec map — any ``core.module.Module`` tree
        annotates ``_pspecs`` and unannotated leaves replicate."""
        if hasattr(model, "shard_for_inference"):
            return model.shard_for_inference(self.mesh)
        import jax

        from paddle_tpu.core.module import partition_specs
        from paddle_tpu.parallel.mesh import sharding_tree
        return jax.device_put(model,
                              sharding_tree(self.mesh,
                                            partition_specs(model)))

    @property
    def replicated(self):
        """NamedSharding replicating a leaf over the whole mesh (None
        for the identity layout — callers only use it under
        ``sharded``)."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def place_pt(self, table):
        """Commit a device-resident page table to the layout: every
        mesh shard needs the full slot->page indirection to gather its
        own KV-head slice, so the table replicates
        (``PAGE_TABLE_SPEC``). Identity layout: ``jax.device_put`` with
        no sharding — a plain committed device array whose ``.at``
        dirty-row updates stay on device between steps."""
        import jax
        if self.mesh is None:
            return jax.device_put(table)
        from jax.sharding import NamedSharding

        from paddle_tpu.models.generation import PAGE_TABLE_SPEC
        return jax.device_put(
            table, NamedSharding(self.mesh, PAGE_TABLE_SPEC))

    def _kv_sharding(self, paged: bool):
        from jax.sharding import NamedSharding

        from paddle_tpu.models.generation import (
            POOL_KV_SPEC, STACKED_KV_SPEC,
        )
        return NamedSharding(self.mesh,
                             POOL_KV_SPEC if paged else STACKED_KV_SPEC)

    def state_sharding(self, state: dict, *, paged: bool):
        """Sharding tree matching the engine state dict: KV leaves on
        the KV-head axis (stacked contiguous layout or paged pool —
        prefix specs, so int8 scale leaves ride along), everything else
        (tokens, positions, keys, sampling params) replicated."""
        import jax
        kv = self._kv_sharding(paged)
        rep = self.replicated
        return {k: (jax.tree_util.tree_map(lambda _: kv, v)
                    if k == "cache" else rep)
                for k, v in state.items()}

    def place_state(self, state: dict, *, paged: bool) -> dict:
        """Commit freshly built engine state to the layout (identity
        when unsharded). Called at construction and on every
        self-healing rebuild — replacement state lands on the mesh,
        never half-placed."""
        if self.mesh is None:
            return state
        import jax
        return jax.device_put(state,
                              self.state_sharding(state, paged=paged))

    # -- compilation -------------------------------------------------------
    def jit_entry(self, fn, state: dict, *, paged: bool, n_in: int,
                  n_out: int, donate: tuple = (0,)):
        """Compile an engine entry point whose FIRST argument and FIRST
        result are the engine state (donated), with ``n_in`` extra
        operands and ``n_out`` extra results, all replicated. Identity
        layout: plain ``jax.jit`` — bit-identical compiled surface to
        the pre-sharding build. Sharded: explicit in/out shardings pin
        the state to the KV-head split so the SPMD partitioner places
        the collectives inside the step instead of resharding at the
        call boundary."""
        import jax
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=donate)
        st = self.state_sharding(state, paged=paged)
        rep = self.replicated
        return jax.jit(fn, donate_argnums=donate,
                       in_shardings=(st,) + (rep,) * n_in,
                       out_shardings=(st,) + (rep,) * n_out)

    def jit_aux(self, fn, *, n_in: int, n_out: int = 1):
        """Compile a stateless helper (the draft-model lookahead):
        replicated in/out on the mesh, plain ``jax.jit`` otherwise."""
        import jax
        if self.mesh is None:
            return jax.jit(fn)
        rep = self.replicated
        out = rep if n_out == 1 else (rep,) * n_out
        return jax.jit(fn, in_shardings=(rep,) * n_in, out_shardings=out)

    # -- observability -----------------------------------------------------
    def describe(self, kv_bytes: int) -> dict:
        """The ``device`` block for engine ``stats()``/serving
        ``health``: platform, device count, mesh axis sizes (degree-1
        axes elided), total and per-device KV bytes — the topology a
        control plane needs for placement, next to the occupancy it
        already had."""
        import jax
        if self.mesh is None:
            return {"platform": jax.devices()[0].platform, "devices": 1,
                    "mesh": None, "kv_bytes": int(kv_bytes),
                    "kv_bytes_per_device": int(kv_bytes)}
        axes = {a: int(s) for a, s in dict(self.mesh.shape).items()
                if int(s) > 1}
        return {"platform": self.mesh.devices.flat[0].platform,
                "devices": int(self.mesh.size),
                "mesh": axes or {"tp": 1},
                "kv_bytes": int(kv_bytes),
                "kv_bytes_per_device": int(kv_bytes) // self.tp}
