"""Tiered, fleet-wide KV page store for disaggregated serving.

The paged engine (PR 6) made KV pages refcounted, position-independent
units; this module makes them *transferable*. A :class:`KVStore` keeps
serialized page frames (``models.generation.serialize_page``) in a
host-RAM LRU tier, written through to an optional spill tier — any
``io.fs`` filesystem, so a local directory for one box or a ``WireFS``
endpoint (``ptfs://host:port/kv``) shared by every replica in the
fleet. Pages are keyed by their radix *chain key*: a hash chain over
the page's token bytes and every ancestor page's token bytes
(:func:`page_chain_keys`), the store-global generalization of the
``_PrefixCache``'s ``(parent_page, token_bytes)`` radix key. Two
replicas that prefill the same prompt prefix derive the same keys, so
a prefix computed (or demoted) anywhere is a fetch — not a recompute —
everywhere.

Mirrors the heterogeneous role split of the reference's heter
parameter server (``distributed/ps/heter.py``): prefill-tier replicas
produce pages into the store, decode-tier replicas consume them at
admission (``serving/engine.py``), and ``StickySession`` failover
upgrades from token replay to KV fetch.

The store is an I/O-side cache, never an authority: every operation
degrades to a miss on spill-tier failure, and a corrupt frame reads as
a miss (``deserialize_page`` validates), so a broken store can slow
serving down but never wrong it.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Sequence

import numpy as np

from paddle_tpu.io.fs import fs_for_path

__all__ = ["KVStore", "page_chain_keys"]


def page_chain_keys(tokens, page_tokens: int,
                    limit: int | None = None) -> list[str]:
    """Radix chain keys for every FULL page of a token sequence.

    ``key[i] = sha1(key[i-1] || tokens[i*P:(i+1)*P])`` over int32 token
    bytes — key ``i`` commits to the entire prefix through page ``i``,
    exactly like the ``_PrefixCache`` radix walk, but replica-
    independent. Partial tail pages get no key: only whole pages are
    ever published, so the null-page sink and half-filled tails can
    never enter the store. ``limit`` caps the number of keys returned
    (admission only wants the first ``cap`` pages).
    """
    toks = np.asarray(tokens, np.int32).reshape(-1)
    n = toks.size // page_tokens
    if limit is not None:
        n = min(n, max(0, int(limit)))
    keys = []
    h = b""
    for i in range(n):
        page = toks[i * page_tokens:(i + 1) * page_tokens].tobytes()
        h = hashlib.sha1(h + page).digest()
        keys.append(h.hex())
    return keys


class KVStore:
    """Two-tier page store: host-RAM LRU over an ``io.fs`` spill tier.

    ``put`` writes through to the spill tier (that write IS the fleet-
    wide publication), so RAM eviction is a pure demotion — the bytes
    survive in the spill tier and ``get`` re-promotes them. Without a
    spill tier the store is replica-local and RAM eviction drops.
    Thread-safe; all counters ride :meth:`snapshot` into engine
    ``stats()`` / health.
    """

    def __init__(self, *, pages: int = 256, spill: str | None = None):
        self._cap = max(1, int(pages))
        self._ram: OrderedDict[str, bytes] = OrderedDict()
        self._lock = threading.Lock()
        self._spill_root = str(spill).rstrip("/") if spill else None
        self._fs = None
        if self._spill_root:
            self._fs = fs_for_path(self._spill_root)
            try:
                self._fs.mkdirs(self._spill_root)
            except Exception:
                pass  # FSService mkdirs is idempotent; races are benign
        self.hits = 0          # get() served (either tier)
        self.spill_hits = 0    # ...of which came from the spill tier
        self.misses = 0        # get() found nothing
        self.puts = 0          # new frames accepted
        self.put_bytes = 0
        self.fetch_bytes = 0   # bytes returned by get()
        self.demotions = 0     # RAM -> spill-backed eviction
        self.dropped = 0       # RAM eviction with no spill tier
        self.probes = 0

    # -- spill tier ----------------------------------------------------

    def _path(self, key: str) -> str:
        return f"{self._spill_root}/{key}.kvpg"

    def _spill_write(self, key: str, frame: bytes) -> None:
        if self._fs is None:
            return
        fd, tmp = tempfile.mkstemp(prefix="kvpg.")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(frame)
            self._fs.upload(tmp, self._path(key))
        except Exception:
            pass  # spill failure degrades to a replica-local entry
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _spill_read(self, key: str) -> bytes | None:
        if self._fs is None:
            return None
        fd, tmp = tempfile.mkstemp(prefix="kvpg.")
        os.close(fd)
        try:
            self._fs.download(self._path(key), tmp)
            with open(tmp, "rb") as f:
                return f.read()
        except Exception:
            return None  # absent or unreachable: a miss, never an error
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _spill_has(self, key: str) -> bool:
        if self._fs is None:
            return False
        try:
            return self._fs.is_file(self._path(key))
        except Exception:
            return False

    # -- public API ----------------------------------------------------

    def put(self, key: str, frame: bytes) -> bool:
        """Insert a page frame. Content-addressed: a key already held
        (either tier) is a no-op. Returns True when the frame was newly
        accepted."""
        with self._lock:
            if key in self._ram:
                self._ram.move_to_end(key)
                return False
            if self._spill_has(key):
                return False
            self._ram[key] = frame
            self.puts += 1
            self.put_bytes += len(frame)
            self._spill_write(key, frame)
            self._shrink_locked()
            return True

    def get(self, key: str) -> bytes | None:
        """Fetch a page frame, promoting spill-tier hits back into
        RAM. Returns None on a miss."""
        with self._lock:
            frame = self._ram.get(key)
            if frame is not None:
                self._ram.move_to_end(key)
            else:
                frame = self._spill_read(key)
                if frame is not None:
                    self.spill_hits += 1
                    self._ram[key] = frame
                    self._shrink_locked()
            if frame is None:
                self.misses += 1
                return None
            self.hits += 1
            self.fetch_bytes += len(frame)
            return frame

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._ram or self._spill_has(key)

    def probe(self, keys: Sequence[str]) -> int:
        """Longest prefix run of ``keys`` present in the store (either
        tier). Chain keys commit to their whole prefix, so the first
        absent key ends the usable run — pages past a hole cannot be
        admitted. Advisory: bumps no hit/miss counters."""
        with self._lock:
            self.probes += 1
            n = 0
            for k in keys:
                if k in self._ram or self._spill_has(k):
                    n += 1
                else:
                    break
            return n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ram_entries": len(self._ram),
                "ram_cap": self._cap,
                "spill": bool(self._spill_root),
                "hits": self.hits, "spill_hits": self.spill_hits,
                "misses": self.misses, "puts": self.puts,
                "put_bytes": self.put_bytes,
                "fetch_bytes": self.fetch_bytes,
                "demotions": self.demotions, "dropped": self.dropped,
                "probes": self.probes,
            }

    def close(self) -> None:
        fs, self._fs = self._fs, None
        if fs is not None and hasattr(fs, "close"):
            try:
                fs.close()
            except Exception:
                pass

    # -- internals -----------------------------------------------------

    def _shrink_locked(self) -> None:
        while len(self._ram) > self._cap:
            self._ram.popitem(last=False)
            if self._fs is not None:
                self.demotions += 1
            else:
                self.dropped += 1
