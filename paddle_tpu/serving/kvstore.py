"""Tiered, fleet-wide KV page store for disaggregated serving.

The paged engine (PR 6) made KV pages refcounted, position-independent
units; this module makes them *transferable*. A :class:`KVStore` keeps
serialized page frames (``models.generation.serialize_page``) in a
host-RAM LRU tier, written through to an optional spill tier — any
``io.fs`` filesystem, so a local directory for one box or a ``WireFS``
endpoint (``ptfs://host:port/kv``) shared by every replica in the
fleet — and can additionally fetch from *peer replicas* over the wire
``kv_get`` op. Pages are keyed by their radix *chain key*: a hash chain
over the page's token bytes and every ancestor page's token bytes
(:func:`page_chain_keys`), the store-global generalization of the
``_PrefixCache``'s ``(parent_page, token_bytes)`` radix key. Two
replicas that prefill the same prompt prefix derive the same keys, so
a prefix computed (or demoted) anywhere is a fetch — not a recompute —
everywhere.

Mirrors the heterogeneous role split of the reference's heter
parameter server (``distributed/ps/heter.py``): prefill-tier replicas
produce pages into the store, decode-tier replicas consume them at
admission (``serving/engine.py``), and ``StickySession`` failover
upgrades from token replay to KV fetch.

The store is an I/O-side cache, never an authority: every operation
degrades to a miss on tier failure, and a corrupt frame reads as a
miss (``deserialize_page`` validates), so a broken store can slow
serving down but never wrong it. The hardening layer (all hard-off by
default) makes that degradation *bounded and observable*:

- **Deadlines** (``fetch_timeout_s``): a cold fetch that outruns its
  budget is abandoned — the caller degrades to a miss (the engine
  recomputes prefill) instead of wedging on a slow tier.
- **Hedging** (``hedge_ms`` + ``peers``): a spill read that hasn't
  answered within the hedge threshold races a peer replica's wire
  ``kv_get``; the first valid frame wins, the loser is abandoned.
- **Per-tier health** (:class:`_TierHealth`): RAM, spill and peer tiers
  each track error/latency EWMAs and — with ``breaker`` > 0 — open a
  circuit breaker after that many consecutive failures, with an
  exponential-backoff half-open probe (the control-plane
  spawner-breaker idiom). A broken spill tier is *skipped*, never
  waited on: puts keep the frame RAM-only and eviction of an
  unspilled frame drops loudly (``degraded_drops``) instead of
  wedging; :attr:`placeable` goes False so the router's ``kv_probe``
  placement stops pinning new sessions here.

Fault sites (``core/fault.py``): ``kvstore.get`` / ``kvstore.put``
fire at the public API (the call degrades to a miss/no-op and books a
RAM-tier failure), ``kvstore.spill`` fires on spill-tier transfers,
``wire.kv_get`` on the peer-tier round-trip.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Callable, Sequence

import numpy as np

from paddle_tpu.core import fault as _fault
from paddle_tpu.core.monitor import stat_add
from paddle_tpu.io.fs import fs_for_path

__all__ = ["KVStore", "page_chain_keys"]


def page_chain_keys(tokens, page_tokens: int,
                    limit: int | None = None) -> list[str]:
    """Radix chain keys for every FULL page of a token sequence.

    ``key[i] = sha1(key[i-1] || tokens[i*P:(i+1)*P])`` over int32 token
    bytes — key ``i`` commits to the entire prefix through page ``i``,
    exactly like the ``_PrefixCache`` radix walk, but replica-
    independent. Partial tail pages get no key: only whole pages are
    ever published, so the null-page sink and half-filled tails can
    never enter the store. ``limit`` caps the number of keys returned
    (admission only wants the first ``cap`` pages).
    """
    toks = np.asarray(tokens, np.int32).reshape(-1)
    n = toks.size // page_tokens
    if limit is not None:
        n = min(n, max(0, int(limit)))
    keys = []
    h = b""
    for i in range(n):
        page = toks[i * page_tokens:(i + 1) * page_tokens].tobytes()
        h = hashlib.sha1(h + page).digest()
        keys.append(h.hex())
    return keys


class _TierHealth:
    """One tier's health book: error/latency EWMAs plus a consecutive-
    failure circuit breaker with exponential-backoff half-open probing
    (the ``control.py`` spawner-breaker idiom, per store tier).

    ``threshold`` <= 0 disables the breaker entirely — :meth:`allow`
    is then a constant True and only the EWMAs/counters update, so the
    default build carries no breaker state machine. No threads: the
    breaker is evaluated lazily at access time."""

    _ALPHA = 0.2          # EWMA smoothing for err rate and latency

    __slots__ = ("name", "threshold", "backoff_s", "ok", "errors",
                 "consec", "opens", "half_opens", "closes", "err_ewma",
                 "lat_ewma_s", "_open_until", "_probing", "_lock")

    def __init__(self, name: str, *, threshold: int = 0,
                 backoff_s: float = 0.5):
        self.name = name
        self.threshold = int(threshold)
        self.backoff_s = max(float(backoff_s), 0.001)
        self.ok = 0
        self.errors = 0
        self.consec = 0           # consecutive failures
        self.opens = 0            # closed -> open transitions
        self.half_opens = 0       # probes granted while open
        self.closes = 0           # open -> closed recoveries
        self.err_ewma = 0.0
        self.lat_ewma_s = 0.0
        self._open_until = 0.0
        self._probing = False
        self._lock = threading.Lock()

    @property
    def breaker_open(self) -> bool:
        """True from the moment the breaker opens until a successful
        (half-open) probe closes it — the half-open window counts as
        open: the tier is unproven."""
        return self.threshold > 0 and self.consec >= self.threshold

    def allow(self) -> bool:
        """May the caller touch this tier right now? False while the
        breaker is open and backing off; after the backoff elapses
        exactly ONE caller gets a half-open trial at a time."""
        if self.threshold <= 0:
            return True
        with self._lock:
            if self.consec < self.threshold:
                return True
            if time.monotonic() < self._open_until or self._probing:
                return False
            self._probing = True      # the half-open trial
            self.half_opens += 1
            return True

    def record(self, ok: bool, dt: float) -> None:
        """Book one tier interaction's outcome + latency. A success
        closes an open breaker; a failure during the half-open trial
        re-opens it with a doubled (capped) backoff."""
        with self._lock:
            a = self._ALPHA
            self.lat_ewma_s += a * (float(dt) - self.lat_ewma_s)
            self.err_ewma += a * ((0.0 if ok else 1.0) - self.err_ewma)
            self._probing = False
            was_open = self.threshold > 0 and self.consec >= self.threshold
            if ok:
                self.ok += 1
                self.consec = 0
                self._open_until = 0.0
                if was_open:
                    self.closes += 1
                return
            self.errors += 1
            self.consec += 1
            if self.threshold > 0 and self.consec >= self.threshold:
                backoff = self.backoff_s * min(
                    2 ** (self.consec - self.threshold), 32)
                self._open_until = time.monotonic() + backoff
                if not was_open:
                    self.opens += 1
                    stat_add(f"kv/breaker_open/{self.name}")

    def state(self) -> str:
        if not self.breaker_open:
            return "closed"
        if time.monotonic() >= self._open_until:
            return "half_open"
        return "open"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ok": self.ok, "errors": self.errors,
                "consec_failures": self.consec,
                "err_ewma": round(self.err_ewma, 4),
                "lat_ewma_ms": round(self.lat_ewma_s * 1e3, 3),
                "state": self.state(),
                "opens": self.opens, "half_opens": self.half_opens,
                "closes": self.closes,
            }


class KVStore:
    """Tiered page store: host-RAM LRU over an ``io.fs`` spill tier,
    with an optional peer-replica tier for hedged fetches.

    ``put`` writes through to the spill tier (that write IS the fleet-
    wide publication), so RAM eviction is a pure demotion — the bytes
    survive in the spill tier and ``get`` re-promotes them. Without a
    spill tier the store is replica-local and RAM eviction drops.
    Thread-safe; all counters and the per-tier health block ride
    :meth:`snapshot` into engine ``stats()`` / health.

    Hardening knobs (all hard-off by default — zero knobs means zero
    helper threads and the byte-identical pre-hardening path):

    - ``fetch_timeout_s``: per-``get`` cold-fetch deadline; an overrun
      abandons the in-flight tier read and answers a (degraded) miss.
    - ``hedge_ms``: latency threshold after which a pending spill read
      is raced against a peer; first valid frame wins.
    - ``breaker`` / ``breaker_backoff_s``: consecutive failures that
      open a tier's circuit breaker, and the half-open probe backoff
      base (doubled per failed probe, capped 32x).
    - ``peers``: peer replicas to fetch from — wire endpoints
      (``host:port``, dialed with the serving ``kv_get`` op) or
      callables ``key -> bytes | None`` (in-process fleets/tests).
    """

    def __init__(self, *, pages: int = 256, spill: str | None = None,
                 fetch_timeout_s: float = 0.0, hedge_ms: float = 0.0,
                 breaker: int = 0, breaker_backoff_s: float = 0.5,
                 peers: Sequence[str | Callable] = ()):
        self._cap = max(1, int(pages))
        self._ram: OrderedDict[str, bytes] = OrderedDict()
        self._lock = threading.Lock()
        self._spill_root = str(spill).rstrip("/") if spill else None
        self._fs = None
        if self._spill_root:
            self._fs = fs_for_path(self._spill_root)
            try:
                self._fs.mkdirs(self._spill_root)
            except Exception:
                pass  # FSService mkdirs is idempotent; races are benign
        self._timeout_s = max(float(fetch_timeout_s), 0.0)
        self._hedge_ms = max(float(hedge_ms), 0.0)
        self._peers = tuple(peers or ())
        self._peer_clients: dict[str, object] = {}
        self._peer_rr = 0
        b = max(int(breaker), 0)
        self._health = {
            # RAM can't meaningfully break (refusing memory helps no
            # one) — it books API-level latency and injected faults;
            # spill and peer get the full breaker.
            "ram": _TierHealth("ram"),
            "spill": _TierHealth("spill", threshold=b,
                                 backoff_s=breaker_backoff_s),
            "peer": _TierHealth("peer", threshold=b,
                                backoff_s=breaker_backoff_s),
        }
        self._cordoned = False
        # keys whose spill write-through was skipped (open breaker) or
        # failed: eviction of these DROPS the bytes (counted loudly)
        # instead of pretending the spill tier holds them
        self._unspilled: set[str] = set()
        self.hits = 0          # get() served (any tier)
        self.spill_hits = 0    # ...of which came from the spill tier
        self.peer_hits = 0     # ...of which came from a peer replica
        self.misses = 0        # get() found nothing
        self.puts = 0          # new frames accepted
        self.put_bytes = 0
        self.fetch_bytes = 0   # bytes returned by get()
        self.demotions = 0     # RAM -> spill-backed eviction
        self.dropped = 0       # RAM eviction with no spill backing
        self.degraded_drops = 0   # ...because the spill tier was broken
        self.timeouts = 0      # cold fetches abandoned at the deadline
        self.hedges = 0        # peer hedges launched
        self.hedge_wins = 0    # ...won by the peer
        self.probes = 0

    # -- health / degradation ------------------------------------------

    def cordon(self) -> None:
        """Administratively mark the store unplaceable (drain): the
        wire ``kv_probe`` answers no-match so the router's KV-locality
        placement stops pinning new sessions to this replica. Existing
        entries still serve."""
        if not self._cordoned:
            self._cordoned = True
            stat_add("kv/cordoned")

    def uncordon(self) -> None:
        self._cordoned = False

    @property
    def cordoned(self) -> bool:
        return self._cordoned

    @property
    def placeable(self) -> bool:
        """False while cordoned or any tier breaker is open — the
        KV-locality placement signal. A store that cannot reliably
        serve its claimed prefix must not attract new pins."""
        return not self._cordoned and not any(
            h.breaker_open for h in self._health.values())

    # -- spill tier ----------------------------------------------------

    def _path(self, key: str) -> str:
        return f"{self._spill_root}/{key}.kvpg"

    def _spill_absent(self, e: BaseException) -> bool:
        """Classify a spill-read error: True means the tier answered
        and the frame is simply ABSENT (a clean miss); False means the
        tier itself failed (degradation — drives the breaker). A
        missing file only counts as absence while the spill ROOT still
        exists: a vanished root (dir deleted, volume gone) is tier
        loss, not a miss."""
        if isinstance(e, ConnectionError):       # includes InjectedFault
            return False
        absent = isinstance(e, FileNotFoundError) or (
            isinstance(e, (RuntimeError, OSError))
            and "FileNotFoundError" in str(e))   # WireFS error surface
        if not absent:
            return False
        try:
            return bool(self._fs.is_dir(self._spill_root))
        except Exception:
            return False

    def _spill_write(self, key: str, frame: bytes) -> bool:
        """Write-through one frame; returns success. Books spill-tier
        health; failures degrade to a replica-local (unspilled) entry."""
        if self._fs is None:
            return False
        t0 = time.monotonic()
        fd, tmp = tempfile.mkstemp(prefix="kvpg.")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(frame)
            _fault.inject("kvstore.spill")
            self._fs.upload(tmp, self._path(key))
            self._health["spill"].record(True, time.monotonic() - t0)
            return True
        except Exception:
            self._health["spill"].record(False, time.monotonic() - t0)
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _spill_read(self, key: str,
                    record: bool = True) -> tuple[bytes | None, bool]:
        """→ ``(frame, failed)``: ``failed`` is True when the tier
        errored (degradation), False on success or clean absence.
        ``record=False`` defers health booking to the caller (the
        hedged/deadlined orchestrator, which must not double-book an
        abandoned read)."""
        if self._fs is None:
            return None, False
        t0 = time.monotonic()
        fd, tmp = tempfile.mkstemp(prefix="kvpg.")
        os.close(fd)
        try:
            _fault.inject("kvstore.spill")
            self._fs.download(self._path(key), tmp)
            with open(tmp, "rb") as f:
                frame = f.read()
            if record:
                self._health["spill"].record(True, time.monotonic() - t0)
            return frame, False
        except Exception as e:
            absent = self._spill_absent(e)
            if record:
                self._health["spill"].record(absent,
                                             time.monotonic() - t0)
            return None, not absent
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _spill_has(self, key: str) -> bool:
        if self._fs is None:
            return False
        t0 = time.monotonic()
        try:
            _fault.inject("kvstore.spill")
            present = self._fs.is_file(self._path(key))
            self._health["spill"].record(True, time.monotonic() - t0)
            return present
        except Exception:
            self._health["spill"].record(False, time.monotonic() - t0)
            return False

    # -- peer tier -----------------------------------------------------

    def _peer_endpoint_get(self, peer: str, key: str) -> bytes | None:
        client = self._peer_clients.get(peer)
        if client is None:
            from paddle_tpu.io.serving import InferenceClient
            client = InferenceClient(
                peer, timeout=(self._timeout_s or 5.0), retries=0)
            self._peer_clients[peer] = client
        try:
            return client.kv_get(key)
        except Exception:
            # a dead connection poisons the cached client: rebuild next
            self._peer_clients.pop(peer, None)
            try:
                client.close()
            except Exception:
                pass
            raise

    def _peer_read(self, key: str,
                   record: bool = True) -> tuple[bytes | None, bool]:
        """Fetch from the peer tier, rotating through ``peers``; the
        first frame wins. → ``(frame, failed)`` like
        :meth:`_spill_read`: ``failed`` only when every peer errored
        (an answered miss means the tier is alive)."""
        if not self._peers:
            return None, False
        t0 = time.monotonic()
        with self._lock:
            self._peer_rr += 1
            rr = self._peer_rr
        order = [self._peers[(rr + i) % len(self._peers)]
                 for i in range(len(self._peers))]
        answered = False
        for peer in order:
            try:
                _fault.inject("wire.kv_get")
                frame = (peer(key) if callable(peer)
                         else self._peer_endpoint_get(peer, key))
            except Exception:
                continue
            answered = True
            if frame is not None:
                if record:
                    self._health["peer"].record(True,
                                                time.monotonic() - t0)
                return frame, False
        if record:
            self._health["peer"].record(answered, time.monotonic() - t0)
        return None, not answered

    # -- cold fetch orchestration (deadline + hedge) -------------------

    def _fetch_cold(self, key: str) -> tuple[bytes | None, str | None,
                                             bool]:
        """RAM missed: consult the spill and peer tiers. → ``(frame,
        tier, degraded)`` where ``degraded`` marks a miss caused by
        tier failure / timeout / open breaker rather than confirmed
        absence. Runs with ``self._lock`` RELEASED."""
        spill_ok = self._fs is not None and self._health["spill"].allow()
        peer_ok = bool(self._peers) and self._health["peer"].allow()
        # a tier skipped because its breaker is open is degradation:
        # the frame may exist but is unreachable right now
        degraded = ((self._fs is not None and not spill_ok)
                    or (bool(self._peers) and not peer_ok))
        if not spill_ok and not peer_ok:
            return None, None, degraded
        if self._timeout_s <= 0 and self._hedge_ms <= 0:
            # unhardened: inline, thread-free — the default path
            if spill_ok:
                frame, failed = self._spill_read(key)
                if frame is not None:
                    return frame, "spill", False
                degraded |= failed
            if peer_ok:
                frame, failed = self._peer_read(key)
                if frame is not None:
                    return frame, "peer", False
                degraded |= failed
            return None, None, degraded
        return self._fetch_race(key, spill_ok, peer_ok, degraded)

    def _fetch_race(self, key: str, spill_ok: bool, peer_ok: bool,
                    degraded: bool) -> tuple[bytes | None, str | None,
                                             bool]:
        """Deadline-bounded, optionally hedged cold fetch. The spill
        read starts first; the peer is launched when there is no spill
        tier, when the spill read misses/fails, or — hedging — when the
        spill read is still pending after ``hedge_ms``. The first valid
        frame wins; the loser (and anything still pending at the
        deadline) is abandoned: its daemon worker's result is discarded
        and its health outcome is booked by the orchestrator as a
        timeout failure, so a silently hung tier still drives its
        breaker."""
        cv = threading.Condition()
        results: dict[str, tuple[bytes | None, bool, float]] = {}
        t0 = time.monotonic()
        deadline = t0 + self._timeout_s if self._timeout_s > 0 else None
        abandoned = {"flag": False}

        def run(tier: str, fn) -> None:
            ts = time.monotonic()
            try:
                frame, failed = fn(key, record=False)
            except Exception:
                frame, failed = None, True
            dt = time.monotonic() - ts
            with cv:
                if not abandoned["flag"]:
                    self._health[tier].record(
                        frame is not None or not failed, dt)
                results[tier] = (frame, failed, dt)
                cv.notify_all()

        def start(tier: str, fn) -> None:
            threading.Thread(target=run, args=(tier, fn), daemon=True,
                             name=f"kv-{tier}-fetch").start()

        launched: list[str] = []
        hedged = False
        if spill_ok:
            start("spill", self._spill_read)
            launched.append("spill")
        else:
            start("peer", self._peer_read)
            launched.append("peer")
        hedge_at = (t0 + self._hedge_ms / 1e3
                    if (self._hedge_ms > 0 and spill_ok and peer_ok)
                    else None)
        with cv:
            while True:
                for tier in ("spill", "peer"):
                    r = results.get(tier)
                    if r is not None and r[0] is not None:
                        if hedged and tier == "peer":
                            with self._lock:
                                self.hedge_wins += 1
                        return r[0], tier, False
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    abandoned["flag"] = True
                    with self._lock:
                        self.timeouts += 1
                    for tier in launched:
                        if tier not in results:
                            self._health[tier].record(False, now - t0)
                    stat_add("kv/fetch_timeouts")
                    return None, None, True
                if (peer_ok and "peer" not in launched
                        and (("spill" in results
                              and results["spill"][0] is None)
                             or (hedge_at is not None
                                 and now >= hedge_at))):
                    # sequential fallback after a spill miss/failure, or
                    # the hedge: race the peer against the pending read
                    hedged = "spill" not in results
                    if hedged:
                        with self._lock:
                            self.hedges += 1
                        stat_add("kv/hedges")
                    start("peer", self._peer_read)
                    launched.append("peer")
                if len(results) == len(launched) and (
                        "peer" in launched or not peer_ok):
                    degraded |= any(r[1] for r in results.values())
                    return None, None, degraded
                waits = [0.05]
                if deadline is not None:
                    waits.append(deadline - now)
                if hedge_at is not None and "peer" not in launched:
                    waits.append(hedge_at - now)
                cv.wait(timeout=max(min(waits), 0.001))

    # -- public API ----------------------------------------------------

    def put(self, key: str, frame: bytes) -> bool:
        """Insert a page frame. Content-addressed: a key already held
        (either tier) is a no-op. Returns True when the frame was newly
        accepted. Spill I/O runs OUTSIDE the store lock, and a spill
        tier with an open breaker is skipped entirely — the frame
        stays RAM-only (``_unspilled``) rather than wedging the caller
        (eviction included) on a sick tier."""
        t0 = time.monotonic()
        try:
            _fault.inject("kvstore.put")
        except Exception:
            self._health["ram"].record(False, time.monotonic() - t0)
            return False
        with self._lock:
            if key in self._ram:
                self._ram.move_to_end(key)
                return False
        spill_up = self._fs is not None and self._health["spill"].allow()
        if spill_up and self._spill_has(key):
            self._health["ram"].record(True, time.monotonic() - t0)
            return False
        wrote = spill_up and self._spill_write(key, frame)
        with self._lock:
            if key in self._ram:       # lost an insert race: no-op
                return False
            self._ram[key] = frame
            self.puts += 1
            self.put_bytes += len(frame)
            if self._fs is not None and not wrote:
                self._unspilled.add(key)
            self._shrink_locked()
        self._health["ram"].record(True, time.monotonic() - t0)
        return True

    def fetch(self, key: str) -> tuple[bytes | None, bool]:
        """Fetch a page frame, promoting cold-tier hits back into RAM.
        → ``(frame, degraded)``: ``degraded`` is True when a miss was
        caused by tier failure, timeout or an open breaker instead of
        confirmed absence — the engine books ``gen/kv_fetch_degraded``
        on it (the recompute debt is degradation, not a cache miss)."""
        t0 = time.monotonic()
        try:
            _fault.inject("kvstore.get")
        except Exception:
            self._health["ram"].record(False, time.monotonic() - t0)
            with self._lock:
                self.misses += 1
            return None, True
        with self._lock:
            frame = self._ram.get(key)
            if frame is not None:
                self._ram.move_to_end(key)
                self.hits += 1
                self.fetch_bytes += len(frame)
        self._health["ram"].record(True, time.monotonic() - t0)
        if frame is not None:
            return frame, False
        frame, tier, degraded = self._fetch_cold(key)
        with self._lock:
            if frame is None:
                self.misses += 1
                return None, degraded
            if tier == "spill":
                self.spill_hits += 1
            elif tier == "peer":
                self.peer_hits += 1
                if self._fs is not None:
                    # a peer frame was never written through locally:
                    # evicting it would lose the bytes — count honestly
                    self._unspilled.add(key)
            self._ram[key] = frame
            self.hits += 1
            self.fetch_bytes += len(frame)
            self._shrink_locked()
            return frame, False

    def get(self, key: str) -> bytes | None:
        """Fetch a page frame; None on a miss (see :meth:`fetch` for
        the degradation-aware form)."""
        return self.fetch(key)[0]

    def contains(self, key: str) -> bool:
        with self._lock:
            if key in self._ram:
                return True
        if self._fs is None or not self._health["spill"].allow():
            return False
        return self._spill_has(key)

    def probe(self, keys: Sequence[str]) -> int:
        """Longest prefix run of ``keys`` present in the store (any
        tier). Chain keys commit to their whole prefix, so the first
        absent key ends the usable run — pages past a hole cannot be
        admitted. Advisory: bumps no hit/miss counters. Spill checks
        run outside the lock and are skipped while the spill breaker
        is open (an unreachable tier answers no-match, it does not
        wedge the prober)."""
        with self._lock:
            self.probes += 1
            ram_keys = set(self._ram)
        spill_ok = self._fs is not None and self._health["spill"].allow()
        n = 0
        for k in keys:
            if k in ram_keys or (spill_ok and self._spill_has(k)):
                n += 1
            else:
                break
        return n

    def snapshot(self) -> dict:
        with self._lock:
            health = {name: h.snapshot()
                      for name, h in self._health.items()}
            return {
                "ram_entries": len(self._ram),
                "ram_cap": self._cap,
                "spill": bool(self._spill_root),
                "peers": len(self._peers),
                "hits": self.hits, "spill_hits": self.spill_hits,
                "peer_hits": self.peer_hits,
                "misses": self.misses, "puts": self.puts,
                "put_bytes": self.put_bytes,
                "fetch_bytes": self.fetch_bytes,
                "demotions": self.demotions, "dropped": self.dropped,
                "degraded_drops": self.degraded_drops,
                "timeouts": self.timeouts,
                "hedges": self.hedges, "hedge_wins": self.hedge_wins,
                "probes": self.probes,
                "errors": sum(h.errors for h in self._health.values()),
                "breaker_opens": sum(h.opens
                                     for h in self._health.values()),
                "cordoned": self._cordoned,
                "degraded": not self.placeable,
                "health": health,
            }

    def close(self) -> None:
        fs, self._fs = self._fs, None
        if fs is not None and hasattr(fs, "close"):
            try:
                fs.close()
            except Exception:
                pass
        clients, self._peer_clients = dict(self._peer_clients), {}
        for client in clients.values():
            try:
                client.close()
            except Exception:
                pass

    # -- internals -----------------------------------------------------

    def _shrink_locked(self) -> None:
        while len(self._ram) > self._cap:
            key, _ = self._ram.popitem(last=False)
            if self._fs is not None and key not in self._unspilled:
                self.demotions += 1
            elif self._fs is not None:
                # demote-to-drop: the spill tier was broken when this
                # frame arrived, so eviction LOSES the bytes — loud
                # (counter + stat), never wedged on the sick tier
                self._unspilled.discard(key)
                self.dropped += 1
                self.degraded_drops += 1
                stat_add("kv/demote_dropped")
            else:
                self.dropped += 1
