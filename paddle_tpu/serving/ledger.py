"""Per-request latency ledger, engine goodput accounting, and per-tenant
attribution (``FLAGS_gen_ledger``, hard-off).

Reference role: the serving-side answer to the reference's profiler +
``tools/timeline.py`` pair — where those reconstruct *per-op* timelines
from profile dumps after the fact, this module attributes *request* and
*engine-loop* wall-clock live, in the categories a serving control plane
actually decides on (Orca's iteration-level accounting, OSDI '22; vLLM's
capacity attribution, SOSP '23). Three books:

- **Request ledger** (:class:`RequestLedger`). Every generation carries
  monotonic phase stamps set at the engine's existing lifecycle sites
  (enqueue → admit → first token → done → delivered) and is finalized
  exactly once at whichever retire path ends it. The record's phase
  durations come from telescoping clamped boundaries, so
  ``admit_wait + prefill + decode + deliver`` partitions the end-to-end
  latency *by construction* — the invariant the tests pin. Resume
  (``rng_skip`` replay) and speculation ride along as sub-phase blocks.
  Each finalize also feeds the ``gen/phase/*_s`` + ``gen/e2e_s``
  histograms, so phase latency percentiles merge fleet-wide through the
  ordinary raw-bucket health path (``MetricsHub.phase_percentiles``).
- **Goodput taxonomy** (:class:`GoodputMeter`). The engine loop notes
  every device section (prefill / decode / spec-verify, or recompile
  when the call's wall clock was an XLA compile) and every deliberate
  wait (admission-idle), then ``tick()`` at each iteration boundary
  sweeps the unaccounted remainder into a hint bucket (host-gather
  normally, watchdog-stuck while the engine is marked stuck). Bucket
  seconds therefore sum to 100% of loop wall-clock; ``goodput`` =
  useful-token time (prefill + decode + spec-verify) / total — the
  direct "compute-bound or stall-bound" signal next to the burn rates.
- **Tenant book** (:class:`TenantBook`). ``tenant=`` on
  ``generate_start``/``infer`` (wire header ``"tn"``) accumulates
  per-tenant tokens, chip-seconds (device wall attributed per request:
  a fused decode step splits evenly across the stepped slots), queue
  wait, and request counts — the consumption input ROADMAP item 6's
  quotas and fairness policies read.

Hard-off discipline: flags are read at construction only. With the
ledger off the engine holds no books and every gate is a single
``is None`` attribute check (the ``FLAGS_trace`` pattern); the serving
path is byte-identical.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from paddle_tpu.core.monitor import observe

__all__ = ["RequestLedger", "GoodputMeter", "TenantBook", "PHASES",
           "GOODPUT_BUCKETS", "GOODPUT_USEFUL"]

# Request phases, in lifecycle order. Durations come from telescoping
# boundaries, so they always sum exactly to the record's e2e_s.
PHASES = ("admit_wait_s", "prefill_s", "decode_s", "deliver_s")

# Engine-loop wall-clock taxonomy. Every loop second lands in exactly
# one bucket; the buckets named in GOODPUT_USEFUL are "useful token
# work" (the goodput numerator). kv_fetch is time spent pulling pages
# from the KV store at admission — it *replaces* prefill compute, but
# it is transfer, not token work, so it stays out of the numerator.
GOODPUT_BUCKETS = ("prefill", "decode", "spec_verify", "host_gather",
                   "admission_idle", "recompile", "watchdog_stuck",
                   "kv_fetch")
GOODPUT_USEFUL = ("prefill", "decode", "spec_verify")

# Untagged traffic books under this tenant key, so fleet totals still
# add up when only some callers send the "tn" header.
DEFAULT_TENANT = "-"


class TenantBook:
    """Per-tenant consumption counters (tokens, chip-seconds, queue
    wait, requests). Thread-safe; shared by the request ledger (engine
    side) and the serving ``infer`` path (server side)."""

    __slots__ = ("_lock", "_tenants")

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: dict[str, dict[str, float]] = {}

    def add(self, tenant: str | None, *, tokens: int = 0,
            chip_s: float = 0.0, queue_wait_s: float = 0.0,
            requests: int = 0) -> None:
        key = str(tenant) if tenant else DEFAULT_TENANT
        with self._lock:
            t = self._tenants.get(key)
            if t is None:
                t = self._tenants[key] = {
                    "tokens": 0, "chip_seconds": 0.0,
                    "queue_wait_s": 0.0, "requests": 0}
            t["tokens"] += int(tokens)
            t["chip_seconds"] += float(chip_s)
            t["queue_wait_s"] += float(queue_wait_s)
            t["requests"] += int(requests)

    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._tenants.items()}


class GoodputMeter:
    """Loop wall-clock taxonomy that sums to 100% by construction.

    The loop thread ``note()``s measured sections as they happen and
    ``tick()``s once per iteration; the tick attributes whatever wall
    time since the previous tick was NOT explicitly noted to the hint
    bucket (host-side gather/bookkeeping normally, ``watchdog_stuck``
    while the engine is latched stuck). Because the remainder is swept
    every tick, bucket seconds always total the elapsed loop time —
    fractions sum to 1.0 whenever any time has passed."""

    __slots__ = ("_lock", "_buckets", "_t0", "_noted", "_ticks")

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
        self._t0 = time.perf_counter()
        self._noted = 0.0
        self._ticks = 0

    def note(self, bucket: str, dt: float) -> None:
        """Attribute ``dt`` seconds of the current iteration to
        ``bucket`` (a measured device call or deliberate wait)."""
        if dt <= 0.0:
            return
        with self._lock:
            self._buckets[bucket] += dt
            self._noted += dt

    def tick(self, hint: str = "host_gather") -> None:
        """Close one loop iteration: sweep the un-noted remainder of
        the wall clock since the last tick into ``hint``."""
        now = time.perf_counter()
        with self._lock:
            rem = (now - self._t0) - self._noted
            if rem > 0.0:
                self._buckets[hint] += rem
            self._t0 = now
            self._noted = 0.0
            self._ticks += 1

    def snapshot(self) -> dict[str, Any]:
        """``{total_s, ticks, buckets, fractions, goodput}`` — the
        ``goodput`` block :meth:`GenerationEngine.stats` ships in
        health (fleet rollup: ``MetricsHub.fleet_goodput``)."""
        with self._lock:
            buckets = dict(self._buckets)
            ticks = self._ticks
        total = sum(buckets.values())
        useful = sum(buckets[b] for b in GOODPUT_USEFUL)
        return {
            "total_s": total,
            "ticks": ticks,
            "buckets": buckets,
            "fractions": {b: (buckets[b] / total if total > 0.0 else 0.0)
                          for b in GOODPUT_BUCKETS},
            "goodput": (useful / total) if total > 0.0 else 0.0,
        }


class RequestLedger:
    """Finalized per-request phase records + the engine's tenant book.

    ``finalize`` is called exactly once per generation (the engine
    guards idempotency with the generation's ``ledgered`` flag, under
    its own lock) at whichever retire path ends it — delivery, cancel,
    TTL reap, engine failure/break, or close. Boundaries telescope:

    ``created <= admitted <= first_token <= done <= end``

    with any missing stamp collapsing to ``end`` and every boundary
    clamped monotone, so the four phase durations sum EXACTLY to
    ``end - created`` (the partition invariant)."""

    __slots__ = ("_lock", "_records", "_book")

    def __init__(self, records: int = 256):
        self._lock = threading.Lock()
        self._records: deque[dict] = deque(maxlen=max(int(records), 1))
        self._book = TenantBook()

    @property
    def book(self) -> TenantBook:
        """The live tenant book (scheduler quota input)."""
        return self._book

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def book_admission(self, gen, now: float | None = None) -> None:
        """Book the generation's queue wait into the tenant book LIVE
        at admission time, so in-flight scheduler decisions see current
        per-tenant waits instead of only finalized ones. Finalize stays
        authoritative: it books the (clamped) remainder, so per-tenant
        ``queue_wait_s`` totals match the finalize-only path exactly."""
        ts = time.monotonic() if now is None else float(now)
        wait = max(ts - gen.created, 0.0)
        # a preempted-and-readmitted generation books here twice: only
        # the delta past the previous booking is added, so the running
        # total never double counts
        self._book.add(gen.tenant,
                       queue_wait_s=max(wait - gen.queue_booked, 0.0))
        gen.queue_booked = wait

    def finalize(self, gen, outcome: str,
                 now: float | None = None) -> dict:
        """Build, store, and return the generation's phase record;
        feed the phase histograms and the tenant book."""
        end = time.monotonic() if now is None else float(now)
        b0 = min(gen.created, end)
        # missing stamps (0.0 — the site never ran) collapse to the
        # end; clamping keeps the chain monotone even under clock
        # jitter, so phase durations are non-negative and telescope
        b1 = min(max(gen.admitted_ts or end, b0), end)
        b2 = min(max(gen.first_tok_ts or end, b1), end)
        b3 = min(max(gen.done_ts or end, b2), end)
        phases = {"admit_wait_s": b1 - b0, "prefill_s": b2 - b1,
                  "decode_s": b3 - b2, "deliver_s": end - b3}
        e2e = end - b0
        rec: dict[str, Any] = {
            "gen_id": gen.gen_id,
            "tenant": gen.tenant or DEFAULT_TENANT,
            "outcome": outcome,
            "e2e_s": e2e,
            "phases": phases,
            "prompt_len": int(gen.prompt.size),
            "tokens": len(gen.tokens),
            "chip_s": gen.chip_s,
        }
        if gen.rng_skip:
            # resume sub-phase: this generation is a failover replay —
            # rng_skip tokens were already delivered by a prior replica,
            # so its prefill phase includes the prefix re-prefill
            rec["resume"] = {"rng_skip": int(gen.rng_skip)}
        if gen.spec_proposed:
            rec["spec"] = {"proposed": int(gen.spec_proposed),
                           "accepted": int(gen.spec_accepted)}
        with self._lock:
            self._records.append(rec)
        # queue wait may have been booked live at admission
        # (book_admission); finalize books only the remainder so the
        # per-tenant total is exactly the authoritative admit_wait_s
        self._book.add(rec["tenant"], tokens=len(gen.tokens),
                       chip_s=gen.chip_s,
                       queue_wait_s=(phases["admit_wait_s"]
                                     - getattr(gen, "queue_booked", 0.0)),
                       requests=1)
        observe("gen/e2e_s", e2e)
        for ph, v in phases.items():
            observe(f"gen/phase/{ph}", v)
        return rec

    def records(self, limit: int | None = None) -> list[dict]:
        """Newest-last finalized records (all, or the last ``limit``)."""
        with self._lock:
            out = list(self._records)
        if limit is not None and limit > 0:
            out = out[-int(limit):]
        return out

    def tenants(self) -> dict[str, dict[str, float]]:
        return self._book.snapshot()
