"""PS-backed sparse embedding serving (``FLAGS_serving_emb``).

Reference role: the inference half of the reference's distributed
``lookup_table`` stack — CTR/recommender models whose embedding tables
are too big for one host keep them on the parameter-server fleet
(``distributed/ps``), and inference replicas pull rows on demand. The
workload class is the PS stack's reason to exist: tiny dense compute,
huge sparse state, extreme QPS.

Three pieces:

- :class:`EmbeddingServingTier` — per-table hot-row LRU
  (``FLAGS_serving_emb_cache_rows`` capacity, ``FLAGS_serving_emb_ttl_s``
  row TTL) over ``PSClient.pull``; misses are batched and de-duplicated
  so a coalesced request pays ONE pull. Rows are stamped with the
  table's published **version** via generation snapshots: each version
  owns its own cache (:class:`_TableGen`), a lookup resolves entirely
  against the generation it grabbed, and a rollover swaps the whole
  generation atomically — no response ever mixes rows of two versions.
- :class:`SparseCTRPredictor` — a DynamicBatcher-compatible endpoint
  (symbolic batch axis) running one de-duplicated lookup + one compiled
  dense-tower step per coalesced batch, and emitting a version column
  alongside the scores so every wire response row is traceable to
  exactly one table version.
- **Online version rollover** — the trainer publishes a new version
  (``PSClient.publish_version``: versioned save dirs + MANIFEST.json
  written BEFORE the version bump, geo-async style); serving replicas
  notice on the existing health tick (:meth:`maybe_rollover`, rate-
  limited internally) or on the version stamped into any pull reply,
  and flip generations in place — in-flight requests finish on the old
  generation, nothing restarts, nothing drops
  (``serving/emb/rollovers``).

Resilience: a PS pull failure serves TTL-expired cached rows as a
last-resort fallback (``serving/emb/stale_serves`` — zero in a healthy
fleet, which ``chaos_check.py sparse-serve`` pins); ids with no cached
row at all re-raise the pull error.

Hard-off: with ``FLAGS_serving_emb`` at the default the server never
constructs the tier and the serving path is byte-identical (the
``FLAGS_trace`` pattern — flags are read at construction only, hot-path
gates are is-None checks).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any

import numpy as np

from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import stat_add

__all__ = ["EmbeddingServingTier", "SparseCTRPredictor"]

# Minimum seconds between published-version polls on the health tick —
# a constant, not a flag: it bounds control-channel chatter against
# fast probers, it is not a tuning surface.
_ROLLOVER_POLL_MIN_S = 0.25

_STAT_KEYS = ("hits", "misses", "pulled_rows", "pulled_bytes",
              "stale_serves", "rollovers", "evictions")


class _TableGen:
    """One table version's generation: the version label plus the LRU
    cache of rows pulled WHILE that version was current. Rollover swaps
    the whole generation object atomically, so a request that snapshot
    the old one keeps resolving against a single version — there is no
    moment where one response mixes rows of two versions."""

    __slots__ = ("version", "cache")

    def __init__(self, version: int):
        self.version = int(version)
        # id -> (row ndarray, monotonic insert ts); OrderedDict is the
        # LRU (move_to_end on hit, popitem(last=False) to evict)
        self.cache: OrderedDict[int, tuple[np.ndarray, float]] = \
            OrderedDict()


class _TableState:
    __slots__ = ("name", "gen", "lock", "stats")

    def __init__(self, name: str, version: int = 0):
        self.name = name
        self.gen = _TableGen(version)
        self.lock = threading.Lock()
        self.stats = {k: 0 for k in _STAT_KEYS}


class EmbeddingServingTier:
    """Hot-row cache + version rollover between inference replicas and
    the PS fleet.

    ``client`` is a :class:`~paddle_tpu.distributed.ps.client.PSClient`
    (or ``InProcClient``) with the serving tables already created/loaded
    server-side. ``cache_rows``/``ttl_s`` default to their flags — read
    HERE, at construction, only.
    """

    def __init__(self, client, *, cache_rows: int | None = None,
                 ttl_s: float | None = None):
        self._client = client
        self._cap = max(int(flag("serving_emb_cache_rows")
                            if cache_rows is None else cache_rows), 1)
        self._ttl = float(flag("serving_emb_ttl_s")
                          if ttl_s is None else ttl_s)
        self._lock = threading.Lock()
        self._tables: dict[str, _TableState] = {}
        self._poll_lock = threading.Lock()
        self._last_poll = 0.0

    # -- lookup (the hot path) ---------------------------------------------
    def lookup(self, table: str, ids) -> tuple[np.ndarray, int]:
        """Resolve ``ids`` (any shape, int64) to embedding rows of shape
        ``ids.shape + (dim,)``, every row from ONE table version (the
        returned int). Cache misses are de-duplicated into one batched
        PS pull; a pull whose reply is stamped with a NEWER published
        version flips the generation and re-resolves the whole request
        there, so the single-version guarantee survives a rollover
        landing mid-request (converges in one retry per flip — versions
        are monotonic)."""
        ids = np.ascontiguousarray(ids, np.int64)
        flat = ids.reshape(-1)
        st = self._table(table)
        while True:
            with st.lock:
                gen = st.gen
            out = self._resolve(st, gen, flat)
            if out is not None:
                rows = out
                return (rows.reshape(ids.shape + (rows.shape[-1],)),
                        gen.version)
            # _resolve flipped to a newer published generation while
            # pulling; loop re-resolves everything at the new version

    def _table(self, name: str) -> _TableState:
        with self._lock:
            st = self._tables.get(name)
            if st is None:
                st = self._tables[name] = _TableState(name)
            return st

    def _resolve(self, st: _TableState, gen: _TableGen,
                 flat: np.ndarray) -> np.ndarray | None:
        """One attempt to resolve ``flat`` entirely against ``gen``.
        Returns the (n, dim) rows, or None when a newer version was
        discovered mid-pull (the caller re-resolves)."""
        now = time.monotonic()
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows_by_id: dict[int, np.ndarray] = {}
        missing: list[int] = []
        with st.lock:
            if st.gen is not gen:
                return None          # raced a rollover before starting
            for i in uniq.tolist():
                e = gen.cache.get(i)
                if e is not None and (self._ttl <= 0
                                      or now - e[1] <= self._ttl):
                    gen.cache.move_to_end(i)
                    rows_by_id[i] = e[0]
                else:
                    missing.append(i)
            st.stats["hits"] += len(rows_by_id)
            st.stats["misses"] += len(missing)
        if missing:
            marr = np.asarray(missing, np.int64)
            try:
                pulled, pver = self._pull(st.name, marr)
            except (ConnectionError, TimeoutError, OSError) as e:
                pulled = self._stale_fallback(st, gen, marr, e)
            else:
                with st.lock:
                    st.stats["pulled_rows"] += int(marr.shape[0])
                    st.stats["pulled_bytes"] += int(pulled.nbytes)
                if pver > gen.version:
                    # the trainer published while we pulled: these rows
                    # are already the NEW version's — flip, seed them,
                    # and re-resolve the request there
                    self._flip(st, pver, seed=(marr, pulled))
                    return None
                self._insert(st, gen, marr, pulled, now)
            for i, r in zip(missing, pulled):
                rows_by_id[i] = np.asarray(r, np.float32)
        if not uniq.size:
            return np.zeros((0, 0), np.float32)
        uniq_rows = np.stack([rows_by_id[i] for i in uniq.tolist()])
        return uniq_rows[inverse]

    def _pull(self, name: str, ids: np.ndarray) -> tuple[np.ndarray, int]:
        pv = getattr(self._client, "pull_versioned", None)
        if pv is not None:
            rows, version = pv(name, ids)
        else:                        # duck-typed clients without versions
            rows, version = self._client.pull(name, ids), 0
        return np.asarray(rows, np.float32), int(version)

    def _insert(self, st: _TableState, gen: _TableGen, ids: np.ndarray,
                rows: np.ndarray, now: float) -> None:
        with st.lock:
            if st.gen is not gen:
                return               # rolled over meanwhile: drop, the
            #                          next request re-pulls at the new gen
            for i, r in zip(ids.tolist(), rows):
                gen.cache[i] = (np.array(r, np.float32), now)
                gen.cache.move_to_end(i)
            while len(gen.cache) > self._cap:
                gen.cache.popitem(last=False)
                st.stats["evictions"] += 1

    def _stale_fallback(self, st: _TableState, gen: _TableGen,
                        ids: np.ndarray, err: BaseException) -> np.ndarray:
        """PS unreachable: serve TTL-expired cached rows rather than
        fail requests whose rows we still hold (counted
        ``serving/emb/stale_serves`` — zero in a healthy fleet). An id
        with no cached row at all re-raises the pull error."""
        out = []
        with st.lock:
            if st.gen is not gen:
                raise err
            for i in ids.tolist():
                e = gen.cache.get(i)
                if e is None:
                    raise err
                out.append(e[0])
            st.stats["stale_serves"] += len(out)
        stat_add("serving/emb/stale_serves", len(out))
        return np.stack(out)

    # -- version rollover ---------------------------------------------------
    def _flip(self, st: _TableState, version: int, seed=None) -> None:
        now = time.monotonic()
        with st.lock:
            if st.gen.version >= version:
                return               # publish is monotonic; never go back
            new = _TableGen(version)
            if seed is not None:
                ids, rows = seed
                for i, r in zip(ids.tolist(),
                                np.asarray(rows, np.float32)):
                    new.cache[i] = (np.array(r, np.float32), now)
            st.gen = new
            st.stats["rollovers"] += 1
        stat_add("serving/emb/rollovers")

    def maybe_rollover(self) -> dict[str, int] | None:
        """Poll the PS's published-version map and flip any table whose
        generation is behind. Driven by the server's health tick (the
        router-prober / controller scrape cadence), rate-limited
        internally to ``_ROLLOVER_POLL_MIN_S`` so fast probers cost
        nothing extra. Returns the version map consulted, or None when
        rate-limited or the PS was unreachable (best-effort — the next
        tick, or any pull reply, catches the flip)."""
        now = time.monotonic()
        with self._poll_lock:
            if now - self._last_poll < _ROLLOVER_POLL_MIN_S:
                return None
            self._last_poll = now
        try:
            versions = self._client.versions()
        except (ConnectionError, TimeoutError, OSError, RuntimeError):
            return None
        for name, v in versions.items():
            with self._lock:
                st = self._tables.get(name)
            if st is not None and int(v) > st.gen.version:
                self._flip(st, int(v))
        return versions

    # -- observability ------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        """Per-table + rolled-up counters (the ``emb`` health block):
        hits/misses/hit_rate, pulled rows/bytes, stale serves,
        rollovers, evictions, and each table's live version +
        cached-row count."""
        with self._lock:
            tables = dict(self._tables)
        out: dict[str, Any] = {"tables": {}}
        total = {k: 0 for k in _STAT_KEYS}
        for name, st in tables.items():
            with st.lock:
                d: dict[str, Any] = dict(st.stats)
                d["version"] = st.gen.version
                d["cached_rows"] = len(st.gen.cache)
            seen = d["hits"] + d["misses"]
            d["hit_rate"] = d["hits"] / seen if seen else 0.0
            out["tables"][name] = d
            for k in total:
                total[k] += d[k]
        out.update(total)
        seen = total["hits"] + total["misses"]
        out["hit_rate"] = total["hits"] / seen if seen else 0.0
        return out


class SparseCTRPredictor:
    """DynamicBatcher-compatible sparse CTR endpoint: one de-duplicated
    PS lookup + one compiled dense-tower step per (coalesced) batch.

    Input: one ``(B, slots)`` int64 array of per-example sparse feature
    ids. Outputs: ``(B, 1)`` float32 scores AND a ``(B, 1)`` int64
    version column stamping the exact table version every row resolved
    at — the wire response itself carries the rollover traceability.
    The batch axis is symbolic (``supports_batching``), so concurrent
    requests coalesce server-side into one lookup + one tower step;
    the batcher's zero-padding rows (id 0) score harmlessly and are
    sliced away before replies. Slot embeddings are sum-pooled in
    numpy, so the jitted tower only ever sees ``(B, emb_dim)`` — XLA
    recompiles stay bounded by the batcher's power-of-two buckets.
    """

    supports_batching = True

    def __init__(self, tier: EmbeddingServingTier, table: str,
                 slots: int, tower=None, *, emb_dim: int = 16,
                 seed: int = 0):
        import jax

        from paddle_tpu.models.ctr import CTRTower

        self._tier = tier
        self._table = str(table)
        self._slots = int(slots)
        self._tower = (CTRTower(emb_dim=emb_dim, seed=seed)
                       if tower is None else tower)
        self._step = jax.jit(lambda m, pooled: m(pooled))
        self.input_specs = [{"shape": [None, self._slots],
                             "dtype": "int64"}]
        self.output_specs = [{"shape": [None, 1], "dtype": "float32"},
                             {"shape": [None, 1], "dtype": "int64"}]
        # warm-tier residency signal for the control plane's LRU: the
        # hot-row cache's worst-case footprint
        self.resident_bytes = int(tier._cap) * int(emb_dim) * 4

    def run(self, ids) -> list[np.ndarray]:
        ids = np.ascontiguousarray(ids, np.int64)
        rows, version = self._tier.lookup(self._table, ids)  # (B, S, D)
        pooled = rows.sum(axis=1)
        scores = np.asarray(self._step(self._tower, pooled),
                            np.float32).reshape(-1, 1)
        ver = np.full((scores.shape[0], 1), int(version), np.int64)
        return [scores, ver]
