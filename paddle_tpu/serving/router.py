"""Health-aware client-side routing across inference-server replicas.

Reference role: the load-balancing tier in front of a Paddle Serving
fleet (N predictor-replica processes behind a router/BRPC channel
group). Here it is a *client library*: :class:`RoutedClient` holds one
:class:`~paddle_tpu.io.serving.InferenceClient` per replica endpoint and
spreads idempotent requests across them:

- **Least-inflight pick** — each wire client counts its submitted-but-
  unanswered requests (``FrameClient.inflight``, per-op via
  ``inflight_by_op()``); a request goes to the healthy replica with the
  fewest, ties broken round-robin. Slow replicas shed load automatically
  without any server cooperation.
- **Health-probe membership** — a daemon thread probes every replica's
  universal ``health`` op (never shed, answered even under overload)
  every ``FLAGS_serving_probe_interval_s``; unreachable or *draining*
  replicas stop receiving new requests and rejoin when the probe sees
  ``ok`` again. ``add_endpoint``/``remove_endpoint`` change membership
  live.
- **Failover** — a connect error/timeout marks the replica down and the
  request retries on the next pick; a :class:`~paddle_tpu.core.wire.
  WireShedError` (admission control turned the request away *before*
  execution) reroutes without marking the replica down. Both are safe
  for the idempotent serving ops this client routes (``infer``,
  ``list_models``, ``load_model``); the shed case is safe for any op.
  Each failing replica is tried at most once per request; when every
  member has failed, the last error surfaces.

Sticky drain (the control plane's scale-down primitive): ``cordon``
excludes a replica from every new pick — routed AND session — while its
pooled connections stay open, so in-flight streams finish on the replica
that holds their state; ``remove_endpoint`` then finalizes.

Stream resumption (``FLAGS_gen_resume_budget``, hard-off): with a
budget set, a generation stream that loses its replica mid-flight —
connection loss, replica death, or a server-side engine reset (the
``engine reset:`` error marker) — is transparently restarted on a
freshly picked replica by replaying ``prompt + tokens already
delivered`` as a prefill-from-prefix (cheap when the radix prefix cache
shares the replayed prefix) and continues emitting from where it broke:
byte-identical for greedy decode, RNG-position-replayed for sampled
streams (the engine's ``rng_skip``). Exhausting the budget surfaces the
typed :class:`StreamResumeExhausted`; a
:class:`~paddle_tpu.serving.engine.RequestQuarantined` rejection is
final and never resumed — a poisoned request must not be walked across
the fleet.

Speculative decoding (``FLAGS_gen_spec_k``) composes with resumption
unchanged: the engine consumes exactly one RNG split per EMITTED token
regardless of how many drafts each verify step accepted, so
``rng_skip = len(delivered)`` lands on the same key schedule whether
the original replica, the resuming replica, both, or neither were
speculating — speculative rollback is per-slot device state the wire
contract never sees (``tools/chaos_check.py`` gen-spec pins this).

Stats: ``serving/router/failovers``, ``serving/router/shed_rerouted``,
``serving/router/marked_down``, ``serving/router/recovered``,
``serving/router/cordoned``, ``serving/router/uncordoned``,
``serving/router/stream_resumes``, ``serving/router/resume_exhausted``.
"""

from __future__ import annotations

import random as _random_mod
import threading
import time
import uuid
import zlib
from typing import Callable

import numpy as np

from paddle_tpu.core import trace as _trace
from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import stat_add
from paddle_tpu.core.wire import FrameClient, WireShedError
from paddle_tpu.io.serving import InferenceClient
from paddle_tpu.serving.engine import (
    EXPIRED_MARKER, RESET_MARKER, GenerationExpired, stream_fingerprint,
)

__all__ = ["RoutedClient", "ReplicaState", "StickySession",
           "GenerationFailed", "StreamResumeExhausted"]

_jitter_rng = _random_mod.Random()


def _jittered(base: float) -> float:
    """U[0.9, 1.1) x base — decorrelates N routers' (and standby
    controllers') probe cadence so they don't synchronize their health
    scrapes into a thundering herd on the fleet (the PR-8 shed-jitter
    idiom, tighter band: a cadence, not a backoff)."""
    return base * (0.9 + 0.2 * _jitter_rng.random())


class GenerationFailed(ConnectionError):
    """A non-idempotent generation op failed on its pinned replica.
    NEVER silently failed over — the generation's slot (KV cache + token
    stream) lives on exactly one replica, so rerouting a poll would
    return "unknown generation" and rerouting a start would leak a slot.
    ``endpoint`` names the replica so the caller can restart the
    generation elsewhere (or let stream resumption do it:
    ``FLAGS_gen_resume_budget``)."""

    def __init__(self, msg: str, endpoint: str):
        super().__init__(msg)
        self.endpoint = endpoint


class StreamResumeExhausted(GenerationFailed):
    """Stream resumption gave up: the generation lost its replica more
    times than ``FLAGS_gen_resume_budget`` allows. ``attempts`` counts
    the restarts tried; ``endpoint`` is the last replica that failed.
    Tokens already yielded to the caller remain valid — the stream is
    merely incomplete."""

    def __init__(self, msg: str, endpoint: str, attempts: int = 0):
        super().__init__(msg, endpoint)
        self.attempts = attempts


class ReplicaState:
    """One replica's routing view: endpoint, a small connection pool
    (lazy, rebuilt after failures), and probe-driven health.

    The pool matters: one ``FrameClient`` serializes its requests behind
    a connection lock, so a single shared connection could never present
    concurrent same-model requests to the replica — exactly what the
    server-side batcher coalesces. N pooled connections let one routed
    client keep N requests in flight per replica.

    ``cordoned`` is the sticky-drain state: a cordoned replica receives
    no NEW picks (routed or session) but keeps its pooled connections
    open, so in-flight work — a streaming generation's polls especially
    — runs to completion. Health probes keep running; ``cordon`` is
    orthogonal to ``healthy`` and survives recovery."""

    __slots__ = ("endpoint", "clients", "healthy", "last_error", "probes",
                 "failures", "cordoned")

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self.clients: list[InferenceClient] = []
        self.healthy = True           # optimistic until a probe/request
        self.last_error: str | None = None
        self.probes = 0
        self.failures = 0
        self.cordoned = False

    @property
    def inflight(self) -> int:
        return sum(c.inflight for c in self.clients)


class RoutedClient:
    """Route idempotent serving requests across replica endpoints.

    ``endpoints`` may be empty at construction and grown later with
    :meth:`add_endpoint`. Per-replica connections are built by
    ``client_factory`` (default: ``InferenceClient(ep, timeout=timeout,
    retries=retries)`` with ``retries=0`` so failover happens at the
    router, not inside one replica's retry loop) and pooled up to
    ``pool_size`` per replica — grown on demand when every pooled
    connection is busy, so concurrent callers reach the replica
    concurrently (a prerequisite for server-side batching to coalesce
    them). ``probe_interval_s`` defaults to
    ``FLAGS_serving_probe_interval_s``; pass 0 to disable background
    probing (membership then only reacts to request errors).
    """

    def __init__(self, endpoints: list[str] | tuple[str, ...] = (), *,
                 timeout: float | None = None, retries: int = 0,
                 probe_interval_s: float | None = None,
                 pool_size: int = 8,
                 client_factory: Callable[[str], InferenceClient]
                 | None = None):
        self._factory = client_factory or (
            lambda ep: InferenceClient(ep, timeout=timeout,
                                       retries=retries))
        self._timeout = timeout
        self._pool_size = max(int(pool_size), 1)
        # KV-locality placement (FLAGS_gen_kv_store, read HERE only —
        # hard-off keeps session pinning byte-identical): with the
        # store on, an unpinned session's first generation probes the
        # healthy replicas' stores (kv_probe) and pins the one holding
        # the longest radix prefix of the prompt — the per-prefix
        # generalization of the load signals
        self._kv_locality = bool(flag("gen_kv_store"))
        self._kv_page_tokens = (int(flag("gen_page_tokens"))
                                if self._kv_locality else 0)
        self._lock = threading.Lock()
        self._replicas: list[ReplicaState] = []
        self._rr = 0                     # round-robin tie-breaker
        self._closed = False
        for ep in endpoints:
            self.add_endpoint(ep)
        if probe_interval_s is None:
            probe_interval_s = float(flag("serving_probe_interval_s"))
        self._probe_interval = float(probe_interval_s)
        self._probe_stop = threading.Event()
        self._prober: threading.Thread | None = None
        if self._probe_interval > 0:
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True)
            self._prober.start()

    # -- membership --------------------------------------------------------
    def add_endpoint(self, endpoint: str) -> None:
        with self._lock:
            if self._closed:
                raise ConnectionError("RoutedClient is closed")
            if any(r.endpoint == endpoint for r in self._replicas):
                return
            self._replicas.append(ReplicaState(endpoint))

    def remove_endpoint(self, endpoint: str) -> None:
        with self._lock:
            keep, drop = [], []
            for r in self._replicas:
                (drop if r.endpoint == endpoint else keep).append(r)
            self._replicas = keep
        for r in drop:
            self._close_clients(r)

    def cordon(self, endpoint: str) -> None:
        """Stop routing NEW requests to ``endpoint`` while keeping its
        pooled connections (and therefore all in-flight work, including
        streaming generations' polls) alive — the first half of a
        sticky-drain scale-down. Unknown endpoints are ignored. The
        replica remains a member (probed, visible in :meth:`members`)
        until :meth:`remove_endpoint` finalizes the removal."""
        with self._lock:
            for r in self._replicas:
                if r.endpoint == endpoint and not r.cordoned:
                    r.cordoned = True
                    stat_add("serving/router/cordoned")

    def uncordon(self, endpoint: str) -> None:
        """Re-admit a cordoned replica to routing (a cancelled drain)."""
        with self._lock:
            for r in self._replicas:
                if r.endpoint == endpoint and r.cordoned:
                    r.cordoned = False
                    stat_add("serving/router/uncordoned")

    def endpoints(self) -> list[str]:
        with self._lock:
            return [r.endpoint for r in self._replicas]

    def members(self) -> list[dict]:
        """Routing snapshot: one dict per replica (endpoint, healthy,
        cordoned, inflight, failures, last_error)."""
        with self._lock:
            return [{"endpoint": r.endpoint, "healthy": r.healthy,
                     "cordoned": r.cordoned,
                     "inflight": r.inflight, "failures": r.failures,
                     "last_error": r.last_error}
                    for r in self._replicas]

    # -- health probing ----------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(_jittered(self._probe_interval)):
            try:
                self.probe()
            except Exception:      # pragma: no cover - prober never dies
                pass

    def probe(self) -> list[dict]:
        """One probe round over current members (also runs on the
        background thread): each replica's ``health`` op decides its
        membership. Returns :meth:`members` afterwards."""
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            ok, err = self._probe_one(r.endpoint)
            with self._lock:
                if r not in self._replicas:    # removed mid-probe
                    continue
                r.probes += 1
                was = r.healthy
                r.healthy = ok
                r.last_error = err
                if ok and not was:
                    stat_add("serving/router/recovered")
        return self.members()

    def _probe_one(self, endpoint: str) -> tuple[bool, str | None]:
        """Probe via a short-lived dedicated connection: the data
        client's lock may be held by a long infer, and a probe must
        never queue behind the traffic it is assessing."""
        timeout = self._timeout if self._timeout is not None else 5.0
        try:
            with FrameClient(endpoint, {}, service="probe",
                             timeout=timeout, retries=0) as c:
                h = c.health(stats=False)    # liveness only, no stats
            if h.get("status") != "ok":
                return False, f"status={h.get('status')}"
            return True, None
        except (ConnectionError, RuntimeError, OSError) as e:
            return False, f"{type(e).__name__}: {e}"

    # -- routing core ------------------------------------------------------
    def _pick(self, exclude: set[str], any_health: bool = False
              ) -> ReplicaState | None:
        """Healthy replica with the fewest in-flight requests (ties:
        round-robin). ``any_health`` is the last resort — membership may
        be stale and a 'down' replica may be back. Cordoned replicas
        are NEVER picked, not even as the last resort: a drain that
        leaked new work would never converge."""
        with self._lock:
            pool = [r for r in self._replicas
                    if r.endpoint not in exclude and not r.cordoned
                    and (any_health or r.healthy)]
            if not pool:
                return None
            self._rr += 1
            lo = min(r.inflight for r in pool)
            ties = [r for r in pool if r.inflight == lo]
            return ties[self._rr % len(ties)]

    def _client(self, r: ReplicaState) -> InferenceClient:
        """An idle pooled connection if one exists; grow the pool while
        every connection is busy (up to ``pool_size``), then share the
        least-loaded one."""
        with self._lock:
            idle = [c for c in r.clients if c.inflight == 0]
            if idle:
                return idle[0]
            grow = len(r.clients) < self._pool_size
            if not grow and r.clients:
                return min(r.clients, key=lambda c: c.inflight)
        client = self._factory(r.endpoint)   # connects; may raise
        with self._lock:
            if len(r.clients) < self._pool_size:
                r.clients.append(client)
                return client
        client.close()                       # lost the race; pool full
        with self._lock:
            return min(r.clients, key=lambda c: c.inflight)

    def _mark_down(self, r: ReplicaState, err: BaseException) -> None:
        stat_add("serving/router/marked_down")
        with self._lock:
            r.healthy = False
            r.failures += 1
            r.last_error = f"{type(err).__name__}: {err}"
        self._close_clients(r)

    def _close_clients(self, r: ReplicaState) -> None:
        with self._lock:
            clients, r.clients = list(r.clients), []
        for client in clients:
            client.close()

    def _routed(self, fn: Callable[[InferenceClient], object]):
        """Run ``fn(client)`` on the best replica, failing over across
        members: connect errors mark the replica down, sheds just
        reroute. Only pass idempotent operations."""
        if self._closed:
            raise ConnectionError("RoutedClient is closed")
        tried: set[str] = set()
        last: BaseException | None = None
        for any_health in (False, True):
            while True:
                r = self._pick(tried, any_health)
                if r is None:
                    break
                tried.add(r.endpoint)
                try:
                    out = fn(self._client(r))
                    with self._lock:      # request-level health signal
                        if not r.healthy:
                            r.healthy = True
                            stat_add("serving/router/recovered")
                    return out
                except WireShedError as e:
                    # rejected BEFORE execution: replica is overloaded
                    # or draining, not dead — reroute, don't mark down
                    stat_add("serving/router/shed_rerouted")
                    last = e
                except (ConnectionError, TimeoutError, OSError) as e:
                    stat_add("serving/router/failovers")
                    self._mark_down(r, e)
                    last = e
        if last is not None:
            raise last
        raise ConnectionError("no replicas available "
                              f"(members: {self.endpoints()})")

    # -- session-sticky routing (generation affinity) ----------------------
    def session(self, session_id: str | None = None) -> "StickySession":
        """A sticky handle: hash ``session_id`` onto one healthy member
        and keep every op there (a generation's slot state is
        replica-local, so its start/poll/cancel MUST hit one replica).
        Re-picks only on member loss, and never while a generation is in
        flight — that surfaces as :class:`GenerationFailed` instead."""
        return StickySession(self, session_id or uuid.uuid4().hex)

    def generate(self, model: str, prompt, max_new_tokens: int, **kw):
        """Streaming generation through a fresh sticky session (see
        :meth:`session` for multi-op affinity). With
        ``FLAGS_gen_resume_budget`` (or ``resume_budget=``) set, the
        stream survives mid-flight replica loss by resuming on a fresh
        replica — byte-identical for greedy decode."""
        return self.session().generate(model, prompt, max_new_tokens,
                                       **kw)

    def _replica_for(self, endpoint: str) -> ReplicaState | None:
        with self._lock:
            for r in self._replicas:
                if r.endpoint == endpoint:
                    return r
        return None

    def _healthy_endpoints(self) -> list[str]:
        with self._lock:
            return sorted(r.endpoint for r in self._replicas
                          if r.healthy and not r.cordoned)

    # -- the routed serving surface ---------------------------------------
    def infer(self, model: str, *inputs,
              tenant: str | None = None) -> list[np.ndarray]:
        return self._routed(
            lambda c: c.infer(model, *inputs, tenant=tenant))

    def list_models(self) -> dict:
        return self._routed(lambda c: c.list_models())

    def load_model(self, name: str, path: str,
                   broadcast: bool = True) -> None:
        """Hot-load on every healthy non-cordoned replica
        (``broadcast=True``, default — replicas should serve the same
        model set) or on one (a draining replica's model set no longer
        matters)."""
        if not broadcast:
            self._routed(lambda c: c.load_model(name, path))
            return
        errors = []
        for r in list(self._replicas):
            if not r.healthy or r.cordoned:
                continue
            try:
                self._client(r).load_model(name, path)
            except (ConnectionError, RuntimeError, OSError) as e:
                errors.append(f"{r.endpoint}: {type(e).__name__}: {e}")
        if errors:
            raise RuntimeError("load_model failed on: " +
                               "; ".join(errors))

    def unload_model(self, name: str,
                     broadcast: bool = True) -> dict[str, bool]:
        """Drop ``name`` fleet-wide (the control plane's cold-tier
        transition). Returns endpoint -> unloaded (False where the model
        was never resident — unload is idempotent per replica). A
        replica refusing with the typed
        :class:`~paddle_tpu.io.serving.ModelBusyError` (requests still
        in its batcher) surfaces in the aggregate error — nothing hangs,
        the caller retries after the queue drains."""
        if not broadcast:
            return {"": bool(self._routed(
                lambda c: c.unload_model(name)))}
        out: dict[str, bool] = {}
        errors = []
        for r in list(self._replicas):
            if not r.healthy or r.cordoned:
                continue
            try:
                out[r.endpoint] = self._client(r).unload_model(name)
            except (ConnectionError, RuntimeError, OSError) as e:
                errors.append(f"{r.endpoint}: {type(e).__name__}: {e}")
        if errors:
            raise RuntimeError("unload_model failed on: " +
                               "; ".join(errors))
        return out

    def health(self, stats_prefix: str | None = None,
               histograms: bool = False,
               deep: bool = False,
               stats: bool = True) -> dict[str, dict]:
        """endpoint -> server health snapshot (unreachable replicas map
        to ``{"status": "unreachable", ...}``); covers cordoned members
        too — the control plane watches a draining victim's in-flight
        work through exactly this. ``stats_prefix``/``histograms`` pass
        through to each server's health op (raw-bucket histograms merge
        fleet-wide via ``monitor.merge_histograms``); ``deep`` asks each
        replica to run a one-token canary decode per generator — engine
        liveness ("device healthy") as distinct from the wire liveness
        ("port open") the shallow probe measures; ``stats=False`` asks
        for liveness-only docs (no stats payload at all)."""
        out = {}
        for r in list(self._replicas):
            ok, err = self._probe_one(r.endpoint)
            if ok:
                try:
                    out[r.endpoint] = self._client(r).health(
                        stats_prefix=stats_prefix, histograms=histograms,
                        deep=deep, stats=stats)
                    continue
                except (ConnectionError, RuntimeError, OSError) as e:
                    err = f"{type(e).__name__}: {e}"
            out[r.endpoint] = {"status": "unreachable", "error": err}
        return out

    def ledger_dump(self, limit: int | None = None) -> dict[str, dict]:
        """endpoint -> performance-attribution dump (the per-replica
        ``ledger_dump`` op: finalized phase records, per-tenant books,
        goodput snapshots — see ``serving/ledger.py``). Unreachable
        replicas map to ``{"status": "unreachable", ...}`` like
        :meth:`health`; replicas running with ``FLAGS_gen_ledger`` off
        contribute empty dumps. ``tools/perf_report.py`` turns this +
        :meth:`health` into the fleet attribution report."""
        out: dict[str, dict] = {}
        for r in list(self._replicas):
            ok, err = self._probe_one(r.endpoint)
            if ok:
                try:
                    out[r.endpoint] = self._client(r).ledger_dump(limit)
                    continue
                except (ConnectionError, RuntimeError, OSError) as e:
                    err = f"{type(e).__name__}: {e}"
            out[r.endpoint] = {"status": "unreachable", "error": err}
        return out

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._probe_stop.set()
        with self._lock:
            self._closed = True
            replicas, self._replicas = list(self._replicas), []
        for r in replicas:
            for client in r.clients:
                client.close()
        if self._prober is not None:
            self._prober.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class StickySession:
    """Session-sticky view of a :class:`RoutedClient`: every op runs on
    ONE pinned replica (``crc32(session_id)`` over the sorted healthy
    membership, so the same session id re-pins to the same replica from
    any client while membership holds).

    Failure semantics differ from the routed path on purpose:

    - the pin is re-evaluated only between generations — member loss
      with no generation in flight re-picks quietly
      (``serving/router/session_repick``);
    - a connect error/timeout during an in-flight generation raises
      :class:`GenerationFailed` carrying the replica endpoint (and marks
      the replica down for the routed traffic) — NEVER a silent retry
      elsewhere: the slot state is gone, the caller must restart;
    - a shed ``generate_start`` (:class:`~paddle_tpu.core.wire.
      WireShedError`) propagates as-is: it never executed, so the caller
      may back off and retry — on this session or a fresh one.
    """

    def __init__(self, router: RoutedClient, session_id: str):
        self._router = router
        self.session_id = session_id
        self._endpoint: str | None = None
        self._active = 0               # generations currently streaming
        self._lock = threading.Lock()

    @property
    def endpoint(self) -> str | None:
        """The pinned replica (None until the first op pins one)."""
        return self._endpoint

    def _pin(self) -> ReplicaState:
        healthy = self._router._healthy_endpoints()
        with self._lock:
            if self._endpoint is not None and self._endpoint not in healthy:
                if self._active:
                    raise GenerationFailed(
                        f"replica {self._endpoint} lost with "
                        f"{self._active} generation(s) in flight on "
                        f"session {self.session_id}; restart them",
                        self._endpoint)
                stat_add("serving/router/session_repick")
                self._endpoint = None
            if self._endpoint is None:
                if not healthy:
                    raise ConnectionError(
                        "no healthy replicas to pin session "
                        f"{self.session_id} (members: "
                        f"{self._router.endpoints()})")
                idx = zlib.crc32(self.session_id.encode()) % len(healthy)
                self._endpoint = healthy[idx]
        r = self._router._replica_for(self._endpoint)
        if r is None:
            raise GenerationFailed(
                f"replica {self._endpoint} removed from membership",
                self._endpoint)
        return r

    def _client(self) -> InferenceClient:
        return self._router._client(self._pin())

    def _kv_place(self, prompt: np.ndarray) -> None:
        """KV-locality placement (FLAGS_gen_kv_store only): pin this
        not-yet-pinned session to the healthy replica whose store holds
        the longest radix-chain prefix of ``prompt`` — its admission
        serves those pages from RAM instead of fetching (or, store-off
        fleetwide, recomputing). Best-effort: probe errors and
        no-match fleets fall back to the crc32 pin; an existing pin is
        never moved (stickiness wins over locality)."""
        with self._lock:
            if self._endpoint is not None:
                return
        from paddle_tpu.serving.kvstore import page_chain_keys
        P = self._router._kv_page_tokens
        if P < 1:
            return
        keys = page_chain_keys(prompt, P,
                               limit=(int(prompt.size) - 1) // P)
        if not keys:
            return
        healthy = self._router._healthy_endpoints()
        if len(healthy) < 2:
            return
        best, best_n = None, 0
        for ep in healthy:
            r = self._router._replica_for(ep)
            if r is None:
                continue
            try:
                n = self._router._client(r).kv_probe(keys)
            except (ConnectionError, TimeoutError, OSError,
                    RuntimeError):
                continue
            if n > best_n:
                best, best_n = ep, n
        if best is not None:
            # revalidate at pin time: the probe loop is slow (network
            # round-trips), and a cordon/mark-down can land between the
            # healthy snapshot above and here — locality must never
            # override liveness
            r = self._router._replica_for(best)
            if r is None or not r.healthy or r.cordoned:
                stat_add("serving/router/kv_place_rejected")
                return
            with self._lock:
                if self._endpoint is None:
                    self._endpoint = best
                    stat_add("serving/router/kv_placements")

    def _wrap(self, fn, *, during_generation: bool):
        ep = self._endpoint
        try:
            return fn()
        except WireShedError:
            raise                     # never executed: safe anywhere
        except (ConnectionError, TimeoutError, OSError) as e:
            if isinstance(e, GenerationFailed):
                raise
            r = self._router._replica_for(ep) if ep else None
            if r is not None:
                self._router._mark_down(r, e)
            if during_generation:
                raise GenerationFailed(
                    f"generation op failed on replica {ep}: "
                    f"{type(e).__name__}: {e} — slot state lost, "
                    "restart the generation", ep or "?") from e
            raise

    def infer(self, model: str, *inputs,
              tenant: str | None = None) -> list[np.ndarray]:
        """Sticky infer (cache/session affinity). Errors surface; the
        next call re-pins if the member was lost."""
        client = self._client()
        return self._wrap(
            lambda: client.infer(model, *inputs, tenant=tenant),
            during_generation=False)

    def health(self) -> dict:
        client = self._client()
        return self._wrap(lambda: client.health(),
                          during_generation=False)

    def generate(self, model: str, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, eos_token_id: int | None = None,
                 seed: int = 0, poll_wait_s: float = 0.25,
                 resume_budget: int | None = None,
                 tenant: str | None = None,
                 priority: str | None = None):
        """Streaming generation pinned to the session's replica: start,
        every poll, and the close-time cancel all hit the replica
        holding the slot. Returns an iterator of token ids.

        ``resume_budget`` (default: ``FLAGS_gen_resume_budget``) turns
        on lossless stream resumption: when the stream breaks mid-flight
        — connection loss, replica death, or a server-side engine reset
        — the session re-pins to a fresh healthy replica and replays
        ``prompt + tokens already delivered`` as a prefill-from-prefix
        (``rng_skip`` replays the sampling-RNG position), continuing the
        stream from where it broke; greedy output is byte-identical to
        an uninterrupted run. More than ``resume_budget`` restarts
        surfaces the typed :class:`StreamResumeExhausted`. A
        :class:`~paddle_tpu.serving.engine.RequestQuarantined` rejection
        is never resumed. Budget 0 — the flag default — keeps the
        original fail-loud behavior byte-identically."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        budget = (int(flag("gen_resume_budget")) if resume_budget is None
                  else int(resume_budget))
        # One stream trace id per LOGICAL stream, minted here so every
        # resume attempt replays the same id onto its replacement
        # replica — obs_dump then merges the stream's whole life across
        # replicas into one trace. Only minted with tracing on.
        trace_id = _trace.new_id() if _trace.enabled() else None
        # The tenant identity likewise rides every resume attempt, so
        # per-tenant ledger counters keep accruing to the same tenant
        # on whichever replica inherits the stream.
        kw = dict(temperature=temperature, top_k=top_k, top_p=top_p,
                  eos_token_id=eos_token_id, seed=seed,
                  poll_wait_s=poll_wait_s, trace_id=trace_id,
                  tenant=tenant, priority=priority)
        if self._router._kv_locality:
            self._kv_place(prompt)
        if budget <= 0:
            return self._stream_once(model, prompt, max_new_tokens, **kw)
        return self._resuming_stream(model, prompt, max_new_tokens,
                                     budget=budget, **kw)

    def _stream_once(self, model: str, prompt, max_new_tokens: int, *,
                     temperature: float, top_k: int, top_p: float,
                     eos_token_id: int | None, seed: int,
                     poll_wait_s: float, rng_skip: int = 0,
                     trace_id: str | None = None,
                     tenant: str | None = None,
                     fingerprint: str | None = None,
                     priority: str | None = None):
        """One pinned stream attempt (the pre-resumption ``generate``
        body). Server-side failures that lost the slot state but left
        the replica up — the ``engine reset:`` marker — surface as
        :class:`GenerationFailed` (resumable), a TTL reap as the typed
        :class:`~paddle_tpu.serving.engine.GenerationExpired`."""
        client = self._client()
        ep = self._endpoint
        gen_id = self._wrap(
            lambda: client.generate_start(
                model, prompt, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
                seed=seed, rng_skip=rng_skip, trace_id=trace_id,
                tenant=tenant, fingerprint=fingerprint,
                priority=priority),
            during_generation=True)
        with self._lock:
            self._active += 1

        def stream():
            n, finished = 0, False
            try:
                while True:
                    doc = self._wrap(
                        lambda: client.generate_poll(
                            model, gen_id, start=n, wait_s=poll_wait_s),
                        during_generation=True)
                    for tok in doc["tokens"]:
                        yield int(tok)
                    n += len(doc["tokens"])
                    if doc["done"]:
                        finished = True
                        err = doc.get("error")
                        if err:
                            if RESET_MARKER in err:
                                # slot state lost to a self-healing
                                # engine reset; the replica is up —
                                # resumable, never silently retried
                                raise GenerationFailed(
                                    f"generation {gen_id} on {ep} "
                                    f"failed: {err}", ep or "?")
                            if EXPIRED_MARKER in err:
                                raise GenerationExpired(
                                    f"generation {gen_id} on {ep}: "
                                    f"{err}")
                            raise RuntimeError(
                                f"generation {gen_id} on {ep} failed: "
                                f"{err}")
                        return
            finally:
                with self._lock:
                    self._active -= 1
                if not finished:
                    try:
                        client.generate_cancel(model, gen_id)
                    except (RuntimeError, ConnectionError, OSError):
                        pass

        return stream()

    def _resuming_stream(self, model: str, prompt, max_new_tokens: int,
                         *, temperature: float, top_k: int, top_p: float,
                         eos_token_id: int | None, seed: int,
                         poll_wait_s: float, budget: int,
                         trace_id: str | None = None,
                         tenant: str | None = None,
                         priority: str | None = None):
        """Drive :meth:`_stream_once` attempts, replaying
        ``prompt + delivered`` onto a freshly pinned replica after each
        mid-flight loss, until the stream completes or the budget is
        exhausted (typed :class:`StreamResumeExhausted`). Delivered
        tokens are never re-yielded; greedy replays are byte-identical
        by the engine's prefill-from-prefix determinism contract, and
        sampled replays pass ``rng_skip=len(delivered)`` so the engine
        fast-forwards the per-(prompt, seed) key schedule to the break
        position. Every replay also carries the ORIGINAL stream's crash
        fingerprint (header ``fp``): the replay prompt grew by the
        delivered tokens and would hash fresh, so without the carry a
        poisoned stream dodges quarantine by failing over."""
        delivered: list[int] = []
        attempts = 0
        last: BaseException | None = None
        fp = stream_fingerprint(prompt, temperature, top_k, top_p, seed)
        while True:
            n0 = len(delivered)
            try:
                if n0 == 0:
                    inner = self._stream_once(
                        model, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, eos_token_id=eos_token_id,
                        seed=seed, poll_wait_s=poll_wait_s,
                        trace_id=trace_id, tenant=tenant,
                        priority=priority)
                else:
                    replay = np.concatenate(
                        [prompt, np.asarray(delivered, np.int32)])
                    if self._router._kv_locality:
                        # KV-native failover: land the resumed stream
                        # on the replica whose store already holds the
                        # longest prefix of the replay — its admission
                        # fetches instead of recomputing prefill
                        self._kv_place(replay)
                    inner = self._stream_once(
                        model, replay, max_new_tokens - n0,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, eos_token_id=eos_token_id,
                        seed=seed, poll_wait_s=poll_wait_s, rng_skip=n0,
                        trace_id=trace_id, tenant=tenant,
                        fingerprint=fp, priority=priority)
                for tok in inner:
                    delivered.append(int(tok))
                    yield int(tok)
                return
            except StreamResumeExhausted:
                raise
            except GenerationFailed as e:
                last = e
            except (ConnectionError, TimeoutError, OSError) as e:
                if attempts == 0 and n0 == 0:
                    raise            # initial start errors keep their type
                last = e             # restart-time failure: consume budget
            if len(delivered) >= max_new_tokens or (
                    eos_token_id is not None and delivered
                    and delivered[-1] == int(eos_token_id)):
                return               # broke after the final token: done
            attempts += 1
            if attempts > budget:
                stat_add("serving/router/resume_exhausted")
                raise StreamResumeExhausted(
                    f"generation stream lost its replica {attempts} "
                    f"time(s), past the resume budget "
                    f"({budget}; FLAGS_gen_resume_budget) — "
                    f"{len(delivered)}/{max_new_tokens} tokens were "
                    f"delivered; last: {type(last).__name__}: {last}",
                    getattr(last, "endpoint", None) or "?",
                    attempts=attempts) from last
            stat_add("serving/router/stream_resumes")
            if trace_id is not None and _trace.enabled():
                # client-side marker in the SAME stream trace: the
                # merged dump shows exactly where the replica switch
                # happened between the dead engine's spans and the
                # survivor's
                with _trace.server_span("gen/stream_resume", trace_id,
                                        None, attempt=attempts,
                                        delivered=len(delivered)):
                    pass
            with self._lock:
                self._endpoint = None    # re-pin over current membership
            time.sleep(min(0.05 * attempts, 0.5))
