"""Subprocess replica entry point for the serving control plane.

``python -m paddle_tpu.serving.replica_main name=/path/to/artifact ...``
starts an :class:`~paddle_tpu.io.serving.InferenceServer` on a free
port with the given saved-model artifacts, prints ``ENDPOINT host:port``
on stdout (the line :class:`~paddle_tpu.serving.control.
SubprocessSpawner` blocks on), and serves until the wire ``stop`` op or
SIGTERM — both drain gracefully (``FLAGS_wire_drain_s``). One replica =
one OS process: its own GIL and XLA runtime, killable with SIGKILL,
which is exactly what the chaos harness wants a dying replica to look
like.

``FLAGS_*`` environment variables apply as usual (the flag registry
reads them at import), so a spawner can configure batching, caps, and
timeouts per fleet through the child environment.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("models", nargs="*", metavar="name=path",
                    help="model artifacts to serve (save_inference_model "
                         "layout)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 picks a free port (the default — the spawner "
                         "reads the ENDPOINT line)")
    args = ap.parse_args(argv)

    from paddle_tpu.core.flags import flag
    from paddle_tpu.io.serving import InferenceServer

    models: dict[str, str] = {}
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not name or not path:
            ap.error(f"bad model spec {spec!r}; expected name=path")
        models[name] = path

    srv = InferenceServer(models, host=args.host, port=args.port).start()
    print(f"ENDPOINT {srv.endpoint}", flush=True)

    def _term(signum, frame):        # scheduler preemption: drain, exit
        srv.stop(drain_s=float(flag("wire_drain_s")))

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    # serve until stopped (wire stop op, or the signal handler above);
    # _thread goes back to None once the accept loop is shut down
    while srv._thread is not None:
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
