"""Subprocess replica entry point for the serving control plane.

``python -m paddle_tpu.serving.replica_main name=/path/to/artifact ...``
starts an :class:`~paddle_tpu.io.serving.InferenceServer` on a free
port with the given saved-model artifacts, prints ``ENDPOINT host:port``
on stdout (the line :class:`~paddle_tpu.serving.control.
SubprocessSpawner` blocks on), and serves until the wire ``stop`` op or
SIGTERM — both drain gracefully (``FLAGS_wire_drain_s``). One replica =
one OS process: its own GIL and XLA runtime, killable with SIGKILL,
which is exactly what the chaos harness wants a dying replica to look
like.

``FLAGS_*`` environment variables apply as usual (the flag registry
reads them at import), so a spawner can configure batching, caps, and
timeouts per fleet through the child environment.

``--gen NAME`` additionally registers a continuous-batching generation
engine under ``NAME``, over a deterministically seeded tiny-Llama
(``--gen-seed``, fixed config): every replica spawned with the same
seed holds byte-identical weights, so greedy streams are comparable —
and resumable — ACROSS replicas without shipping an artifact. This is
the chaos/test path for killing a subprocess replica that holds a live
stream (``tools/chaos_check.py gen-resilience``); real deployments
register generators in their own entry point.

``--mesh-tp N`` builds that engine over an N-device tensor-parallel
mesh (``serving/layout.py``) while the replica stays one endpoint —
streams remain byte-identical to unsharded replicas, so a router can
fail a stream over between sharded and unsharded members freely
(``tools/chaos_check.py gen-sharded``).

``--kv-store --role prefill|decode|both`` joins the replica to the
disaggregated prefill/decode tier split (``serving/kvstore.py``):
with ``--kv-spill-dir`` pointing every member at one shared root, a
prefix prefilled on any replica is a KV fetch — not a recompute — on
every other, and a killed decode replica's streams resume elsewhere
with zero recomputed prefill tokens (``tools/chaos_check.py
gen-disagg``).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("models", nargs="*", metavar="name=path",
                    help="model artifacts to serve (save_inference_model "
                         "layout)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 picks a free port (the default — the spawner "
                         "reads the ENDPOINT line)")
    ap.add_argument("--gen", default=None, metavar="NAME",
                    help="register a generation engine under NAME over a "
                         "deterministically seeded tiny-Llama (chaos/test "
                         "replicas; same --gen-seed => same weights on "
                         "every replica)")
    ap.add_argument("--gen-seed", type=int, default=7)
    ap.add_argument("--gen-slots", type=int, default=2)
    ap.add_argument("--gen-max-len", type=int, default=32)
    ap.add_argument("--gen-step-wait-s", type=float, default=0.0,
                    help="engine pacing knob (slows decode so chaos "
                         "harnesses can kill a replica mid-stream)")
    ap.add_argument("--gen-paged", action="store_true",
                    help="paged KV cache for the --gen engine")
    ap.add_argument("--gen-page-tokens", type=int, default=8)
    ap.add_argument("--gen-device-pt", action="store_true",
                    help="device-resident page table for the --gen "
                         "engine (FLAGS_gen_device_pt per replica); "
                         "inert unless --gen-paged")
    ap.add_argument("--gen-async-depth", type=int, default=0,
                    help="async double-buffered decode dispatch depth "
                         "for the --gen engine (FLAGS_gen_async_depth "
                         "per replica; 0 = synchronous loop, the "
                         "default). Token streams stay byte-identical")
    ap.add_argument("--gen-spec-k", type=int, default=0,
                    help="speculative decoding lookahead for the --gen "
                         "engine (0 = off, the default)")
    ap.add_argument("--gen-spec-mode", default="ngram",
                    choices=("ngram", "draft"),
                    help="drafter for --gen-spec-k>0; 'draft' builds a "
                         "1-layer draft Llama from the same --gen-seed")
    ap.add_argument("--mesh-tp", type=int, default=0,
                    help="tensor-parallel degree of the --gen engine's "
                         "device mesh (FLAGS_gen_mesh_tp per replica; "
                         "0 = unsharded). The replica stays ONE "
                         "endpoint; token streams are byte-identical "
                         "to unsharded replicas")
    ap.add_argument("--role", default=None,
                    choices=("prefill", "decode", "both"),
                    help="disaggregated serving tier of the --gen "
                         "engine (FLAGS_gen_role per replica; default "
                         "'both'). Inert unless the KV store is on")
    ap.add_argument("--kv-store", action="store_true",
                    help="enable the tiered KV page store for the "
                         "--gen engine (FLAGS_gen_kv_store per "
                         "replica); point --kv-spill-dir (or the "
                         "FLAGS_gen_kv_spill_dir environment) at a "
                         "shared root to make it fleet-wide")
    ap.add_argument("--kv-spill-dir", default=None,
                    help="KV store spill-tier root: a shared directory "
                         "or a ptfs:// WireFS endpoint")
    ap.add_argument("--kv-fetch-timeout-s", type=float, default=None,
                    help="per-page cold-fetch deadline for the KV "
                         "store (FLAGS_gen_kv_fetch_timeout_s per "
                         "replica); overruns degrade to recompute")
    ap.add_argument("--kv-hedge-ms", type=float, default=None,
                    help="hedged-fetch latency threshold "
                         "(FLAGS_gen_kv_hedge_ms per replica): a "
                         "pending spill read races a --kv-peers "
                         "replica after this many ms")
    ap.add_argument("--kv-breaker", type=int, default=None,
                    help="consecutive failures opening a KV tier "
                         "circuit breaker (FLAGS_gen_kv_breaker per "
                         "replica; 0 = no breakers)")
    ap.add_argument("--kv-breaker-backoff-s", type=float, default=None,
                    help="half-open probe backoff base for an open KV "
                         "tier breaker "
                         "(FLAGS_gen_kv_breaker_backoff_s per replica)")
    ap.add_argument("--kv-peers", default=None,
                    help="comma-separated peer replica endpoints for "
                         "the KV store's peer tier "
                         "(FLAGS_gen_kv_peers per replica)")
    ap.add_argument("--gen-sched", action="store_true",
                    help="enable the SLO-aware tenant-fair scheduler "
                         "for the --gen engine (FLAGS_gen_sched per "
                         "replica): priority classes on the 'pc' "
                         "header, weighted-fair queueing across "
                         "tenants, interactive-over-batch preemption "
                         "with byte-identical resume")
    ap.add_argument("--gen-sched-quotas", default=None,
                    help="per-tenant quota shares for the scheduler as "
                         "'tenant=share,...' (FLAGS_gen_sched_quotas "
                         "per replica)")
    ap.add_argument("--gen-sched-headroom", type=int, default=None,
                    help="interactive shed headroom past the queue/"
                         "inflight caps (FLAGS_gen_sched_headroom per "
                         "replica)")
    ap.add_argument("--emb-ps", default=None, metavar="ENDPOINTS",
                    help="comma-separated parameter-server endpoints: "
                         "attach the embedding serving tier "
                         "(FLAGS_serving_emb per replica) and register "
                         "a CTR model whose sparse tables live on the "
                         "PS fleet (tools/chaos_check.py sparse-serve)")
    ap.add_argument("--emb-table", default="emb:16:4",
                    metavar="NAME:DIM[:SLOTS]",
                    help="PS table the --emb-ps CTR model looks up "
                         "(default emb:16:4)")
    ap.add_argument("--emb-model", default="ctr",
                    help="model name the --emb-ps predictor serves "
                         "under (default ctr)")
    ap.add_argument("--emb-seed", type=int, default=0,
                    help="dense-tower seed for --emb-ps (same seed => "
                         "byte-identical tower on every replica)")
    ap.add_argument("--emb-cache-rows", type=int, default=None,
                    help="hot-row cache capacity per table "
                         "(FLAGS_serving_emb_cache_rows per replica)")
    ap.add_argument("--emb-ttl-s", type=float, default=None,
                    help="hot-row TTL within a table version "
                         "(FLAGS_serving_emb_ttl_s per replica; <=0 "
                         "never expires)")
    args = ap.parse_args(argv)

    if args.mesh_tp > 0:
        # a subprocess replica does not inherit a test harness's forced
        # host device count, and XLA reads the flag once at backend
        # init — set it BEFORE anything imports jax so a tp>1 mesh has
        # devices to stand on even on a plain CPU host. Respect an
        # explicit parent setting (real TPU fleets pass topology via
        # the environment).
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            n = max(args.mesh_tp, 8)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={n}").strip()

    from paddle_tpu.core.flags import flag, set_flags
    from paddle_tpu.io.serving import InferenceServer

    # running as ``python -m`` imports the paddle_tpu package (and
    # with it the flag registry) BEFORE main() runs, so an env export
    # here would be read too late — set the flags directly; the engine
    # reads them at construction
    kv_flags = {
        "gen_kv_spill_dir": args.kv_spill_dir,
        "gen_kv_fetch_timeout_s": args.kv_fetch_timeout_s,
        "gen_kv_hedge_ms": args.kv_hedge_ms,
        "gen_kv_breaker": args.kv_breaker,
        "gen_kv_breaker_backoff_s": args.kv_breaker_backoff_s,
        "gen_kv_peers": args.kv_peers,
    }
    kv_flags = {k: v for k, v in kv_flags.items() if v is not None}
    if args.gen_sched:
        kv_flags["gen_sched"] = True
    if args.gen_sched_quotas is not None:
        kv_flags["gen_sched_quotas"] = args.gen_sched_quotas
    if args.gen_sched_headroom is not None:
        kv_flags["gen_sched_headroom"] = args.gen_sched_headroom
    if args.emb_ps:
        kv_flags["serving_emb"] = True
        if args.emb_cache_rows is not None:
            kv_flags["serving_emb_cache_rows"] = args.emb_cache_rows
        if args.emb_ttl_s is not None:
            kv_flags["serving_emb_ttl_s"] = args.emb_ttl_s
    if kv_flags:
        set_flags(kv_flags)

    models: dict[str, str] = {}
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not name or not path:
            ap.error(f"bad model spec {spec!r}; expected name=path")
        models[name] = path

    srv = InferenceServer(models, host=args.host, port=args.port)
    if args.gen:
        import paddle_tpu
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle_tpu.seed(args.gen_seed)
        cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32,
                               num_layers=2, num_heads=2, num_kv_heads=2,
                               max_seq_len=64)
        model = LlamaForCausalLM(cfg)
        draft = None
        if args.gen_spec_k > 0 and args.gen_spec_mode == "draft":
            # deterministically derived from the same seed stream, so
            # every replica drafts identically too
            dcfg = LlamaConfig.tiny(vocab_size=96, hidden_size=16,
                                    num_layers=1, num_heads=2,
                                    num_kv_heads=2, max_seq_len=64)
            draft = LlamaForCausalLM(dcfg)
        srv.add_generator(args.gen, model,
                          slots=args.gen_slots,
                          max_len=args.gen_max_len,
                          step_wait_s=args.gen_step_wait_s,
                          paged=args.gen_paged,
                          page_tokens=args.gen_page_tokens,
                          device_pt=args.gen_device_pt,
                          async_depth=args.gen_async_depth,
                          spec_k=args.gen_spec_k,
                          spec_mode=args.gen_spec_mode,
                          draft_model=draft,
                          mesh_tp=args.mesh_tp,
                          kv_store=(True if args.kv_store else None),
                          role=args.role)
    if args.emb_ps:
        from paddle_tpu.distributed.ps.client import PSClient
        from paddle_tpu.serving.sparse import SparseCTRPredictor

        spec = args.emb_table.split(":")
        tname = spec[0]
        dim = int(spec[1]) if len(spec) > 1 else 16
        slots = int(spec[2]) if len(spec) > 2 else 4
        ps = PSClient([e.strip() for e in args.emb_ps.split(",")
                       if e.strip()])
        tier = srv.attach_embeddings(ps)
        srv.add_model(args.emb_model,
                      SparseCTRPredictor(tier, tname, slots,
                                         emb_dim=dim, seed=args.emb_seed))
    srv.start()
    print(f"ENDPOINT {srv.endpoint}", flush=True)
    # after ENDPOINT (the line SubprocessSpawner blocks on): lets an
    # operator or HA journal record the pid of a replica started by
    # hand, so an adopting leader can escalate a stop past the wire
    print(f"PID {os.getpid()}", flush=True)

    def _term(signum, frame):        # scheduler preemption: drain, exit
        srv.stop(drain_s=float(flag("wire_drain_s")))

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    # serve until stopped (wire stop op, or the signal handler above);
    # _thread goes back to None once the accept loop is shut down
    while srv._thread is not None:
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
