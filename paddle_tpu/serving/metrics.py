"""Fleet metrics hub: a windowed in-memory TSDB for control loops.

Reference role: the fleet half of ``paddle/fluid/platform/monitor.h`` —
the reference exported its global ``StatRegistry`` per process and left
cross-host aggregation to external scrapers; here the
:class:`ServingController` IS the scraper, so the aggregation layer
lives in-process. Each controller tick feeds every replica's ``health``
snapshot into the hub; the hub turns cumulative counters and histogram
totals into **per-tick deltas** (reset-aware: a restarted replica's
counters going backwards clamp to zero instead of producing a giant
negative spike) and answers windowed queries over them:

- ``window_histogram(name, ticks)`` — exact merged distribution of the
  last N ticks' observations across the whole fleet (possible because
  every process shares ``monitor._BUCKET_BOUNDS``),
- ``rate(name, ticks)`` — fleet-wide counter rate per second,
- ``burn_rates(name, threshold)`` — multi-window SLO **burn rate**: the
  fraction of windowed observations violating ``threshold``, divided by
  the error budget.  The violating fraction linearly interpolates the
  mass of the bucket the threshold lands in
  (:func:`~paddle_tpu.core.monitor.hist_fraction_above`), so an SLO
  threshold falling mid-bucket no longer hides up to that bucket's
  whole mass from the burn — the old all-below rounding is available as
  ``conservative=True``.  Burn 1.0 means the budget is being consumed
  exactly as fast as allowed; the controller requires BOTH a fast
  (acute) and a slow (sustained) window above
  ``FLAGS_control_burn_threshold`` before declaring TTFT pressure — the
  standard multi-window burn-rate alert, replacing the old single-tick
  raw-p99 breach check that chased noise.

With ``FLAGS_gen_ledger`` on, engine health docs additionally carry the
request-ledger signals (``serving/ledger.py``) and the hub rolls them
up fleet-wide: ``phase_percentiles()`` merges the per-phase latency
histograms every finalized generation observes (typed
:class:`PhasesNotReady` — not a bare ``{}`` — when nothing merged yet),
``tenants()`` sums the per-tenant consumption gauges, and
``fleet_goodput()`` combines the engines' loop-time taxonomies into one
fleet goodput fraction.  With ``FLAGS_gen_kv_store`` on, ``fleet_kv()``
likewise sums the engines' KV-store gauge blocks into the fleet hit
rate / fetch-bytes / demotion scoreboard.

Membership churn is survivable by construction: an endpoint's first
snapshot is a baseline (no delta), an endpoint that disappears simply
stops contributing new deltas, and its state is pruned after a full
slow window of absence.  An endpoint RE-ADDED after such an absence
(a replica cordoned away and readopted, an HA takeover) re-baselines
instead of differencing the whole gap's cumulative counters into one
bogus window delta.  Gauge-like per-model engine stats
(``health()["generators"]``) are kept as labeled (endpoint, model)
last-value series.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from paddle_tpu.core.monitor import hist_fraction_above, merge_histograms

__all__ = ["MetricsHub", "PhasesNotReady", "hist_delta"]


class PhasesNotReady(dict):
    """Typed empty result from :meth:`MetricsHub.phase_percentiles`:
    nothing merged this window.  A dict subclass so it JSON-serializes
    through health/report paths, and **falsy** (it holds no phase
    entries) so ``if pct:`` call sites behave exactly as with the old
    bare ``{}`` — but it carries the diagnosis the bare dict silently
    dropped: ``ticks_observed`` maps endpoint -> health ticks ingested.
    Cumulative histograms need two ticks to difference into a window
    delta, so an endpoint below 2 explains the emptiness ("not ready
    yet"); every endpoint at >= 2 with still nothing means the request
    ledger is off (or idle) fleet-wide."""

    __slots__ = ("ticks_observed",)

    def __init__(self, ticks_observed: dict[str, int]):
        super().__init__()
        self.ticks_observed = dict(ticks_observed)

    @property
    def not_ready(self) -> bool:
        return True

    @property
    def waiting(self) -> list[str]:
        """Endpoints that cannot contribute yet (fewer than two ticks)."""
        return sorted(ep for ep, n in self.ticks_observed.items() if n < 2)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"PhasesNotReady(ticks_observed={self.ticks_observed!r})"


def hist_delta(prev: dict | None, cur: dict | None) -> dict | None:
    """Per-window histogram delta from two cumulative raw snapshots
    (``export_histograms(raw=True)`` docs): what was observed *between*
    them.  None when there is nothing to diff — no current snapshot, no
    raw buckets, no previous snapshot (first sight is a baseline), or an
    empty window.  Negative bucket deltas (endpoint restarted, counters
    reset) clamp to zero, so a replica bounce reads as an empty window
    instead of poisoning the merge."""
    if not cur or not cur.get("buckets"):
        return None
    if not prev or not prev.get("buckets"):
        return None                      # first sight: baseline only
    buckets = [max(int(c) - int(p), 0)
               for c, p in zip(cur["buckets"], prev["buckets"])]
    count = sum(buckets)
    if count == 0:
        return None                      # nothing happened this window
    return {
        "buckets": buckets,
        "count": count,
        "sum": max(float(cur.get("sum", 0.0))
                   - float(prev.get("sum", 0.0)), 0.0),
        # min/max are cumulative (not diffable); the current snapshot's
        # values are the best available bounds for quantile clamping
        "min": float(cur.get("min", 0.0)),
        "max": float(cur.get("max", 0.0)),
    }


class _EndpointSeries:
    """Per-endpoint state: last cumulative snapshots (the delta
    baselines), a ring of per-tick deltas, and latest per-model gauges.
    Mutated only under the owning hub's lock."""

    __slots__ = ("prev_hists", "prev_stats", "ticks", "gauges", "emb",
                 "last_tick")

    def __init__(self, slow_ticks: int):
        self.prev_hists: dict[str, dict] = {}
        self.prev_stats: dict[str, float] = {}
        # (tick, ts, hist_deltas, stat_deltas) — slow window bounds it
        self.ticks: deque[tuple[int, float, dict, dict]] = deque(
            maxlen=max(slow_ticks, 1))
        self.gauges: dict[str, dict[str, Any]] = {}
        # latest embedding-tier gauge block (FLAGS_serving_emb replicas
        # ship it in health as "emb"); None on replicas without the tier
        self.emb: dict[str, Any] | None = None
        self.last_tick = 0

    def ingest(self, tick: int, ts: float, doc: dict) -> None:
        self.last_tick = tick
        hists = doc.get("histograms") or {}
        h_deltas: dict[str, dict] = {}
        for name, cur in hists.items():
            d = hist_delta(self.prev_hists.get(name), cur)
            if d is not None:
                h_deltas[name] = d
        self.prev_hists = {n: c for n, c in hists.items()
                           if isinstance(c, dict)}
        stats = doc.get("stats") or {}
        s_deltas: dict[str, float] = {}
        for name, cur in stats.items():
            if not isinstance(cur, (int, float)):
                continue
            prev = self.prev_stats.get(name)
            if prev is not None:         # first sight is a baseline
                s_deltas[name] = max(float(cur) - float(prev), 0.0)
        self.prev_stats = {n: float(v) for n, v in stats.items()
                           if isinstance(v, (int, float))}
        gens = doc.get("generators")
        if isinstance(gens, dict):
            self.gauges = {m: dict(g) for m, g in gens.items()
                           if isinstance(g, dict)}
        emb = doc.get("emb")
        if isinstance(emb, dict):
            self.emb = dict(emb)
        self.ticks.append((tick, ts, h_deltas, s_deltas))

    def window(self, tick: int, ticks: int):
        """Delta tuples within the last ``ticks`` hub ticks."""
        lo = tick - max(int(ticks), 1)
        return [t for t in self.ticks if t[0] > lo]


class MetricsHub:
    """Windowed fleet TSDB fed by controller health scrapes.

    ``fast_ticks``/``slow_ticks`` are the two burn-rate windows (in hub
    ingests, i.e. controller ticks).  Short histories are not an error:
    every windowed query uses however many ticks actually exist, so the
    hub gives sane answers from the second tick onward."""

    def __init__(self, fast_ticks: int = 5, slow_ticks: int = 60):
        self.fast_ticks = max(int(fast_ticks), 1)
        self.slow_ticks = max(int(slow_ticks), self.fast_ticks)
        self._lock = threading.Lock()
        self._tick = 0
        self._series: dict[str, _EndpointSeries] = {}

    # -- ingestion ---------------------------------------------------------
    def ingest(self, healths: dict[str, dict]) -> int:
        """One hub tick: feed ``{endpoint: health_doc}`` (unreachable or
        malformed docs are skipped — the endpoint just misses the tick),
        prune endpoints gone a full slow window, return the tick id."""
        ts = time.monotonic()
        with self._lock:
            self._tick += 1
            for ep, doc in healths.items():
                if (not isinstance(doc, dict)
                        or doc.get("status") == "unreachable"):
                    continue
                s = self._series.get(ep)
                if (s is not None
                        and self._tick - s.last_tick > self.slow_ticks):
                    # re-adoption after a full slow window of absence:
                    # ingestion (which refreshes last_tick) runs before
                    # the prune sweep below, so a returning endpoint
                    # would dodge its own prune and difference the
                    # WHOLE gap's cumulative counters against stale
                    # baselines — one giant bogus window delta. Treat
                    # it as brand new: first sight is a baseline.
                    s = None
                if s is None:
                    s = self._series[ep] = _EndpointSeries(
                        self.slow_ticks)
                s.ingest(self._tick, ts, doc)
            gone = [ep for ep, s in self._series.items()
                    if self._tick - s.last_tick > self.slow_ticks]
            for ep in gone:
                del self._series[ep]
            return self._tick

    # -- queries -----------------------------------------------------------
    def window_histogram(self, name: str,
                         ticks: int | None = None) -> dict | None:
        """Merged raw-bucket summary of ``name`` over the last N ticks
        across every endpoint, or None when nothing was observed."""
        with self._lock:
            docs = [d[2][name]
                    for s in self._series.values()
                    for d in s.window(self._tick, ticks or self.fast_ticks)
                    if name in d[2]]
        if not docs:
            return None
        return merge_histograms(docs, raw=True)

    def rate(self, name: str, ticks: int | None = None) -> float:
        """Fleet-wide counter rate (units/second) of ``name`` over the
        last N ticks; 0.0 without enough history to span time."""
        with self._lock:
            total = 0.0
            t_lo, t_hi = None, None
            for s in self._series.values():
                for tick, ts, _h, sd in s.window(self._tick,
                                                 ticks or self.fast_ticks):
                    total += sd.get(name, 0.0)
                    t_lo = ts if t_lo is None else min(t_lo, ts)
                    t_hi = ts if t_hi is None else max(t_hi, ts)
        if t_lo is None or t_hi is None or t_hi <= t_lo:
            return 0.0
        return total / (t_hi - t_lo)

    def burn_rates(self, name: str, threshold: float,
                   budget: float, tenant: str | None = None
                   ) -> tuple[float, float]:
        """(fast, slow) SLO burn rates for histogram ``name`` against
        ``threshold``: violating-fraction / ``budget`` per window.  No
        observations in a window → 0.0 (no traffic burns no budget).

        ``tenant=`` narrows to the per-tenant split of the histogram
        (``<name>/<tenant>`` — the engine observes e.g.
        ``gen/ttft_s/<tn>`` next to the fleet-wide series when a
        tenant header rode the request), so fairness decisions can
        cite per-tenant SLO burn rather than only fleet-wide."""
        if tenant:
            name = f"{name}/{tenant}"
        burns = []
        for w in (self.fast_ticks, self.slow_ticks):
            h = self.window_histogram(name, w)
            frac = hist_fraction_above(h, threshold) if h else 0.0
            burns.append(frac / budget if budget > 0 else 0.0)
        return burns[0], burns[1]

    def gauges(self) -> dict[str, dict[str, dict[str, Any]]]:
        """Latest (endpoint → model → engine-stats) gauge series."""
        with self._lock:
            return {ep: {m: dict(g) for m, g in s.gauges.items()}
                    for ep, s in self._series.items()}

    # -- request-ledger rollups (FLAGS_gen_ledger) -------------------------
    #: histograms the request ledger observes per finalized generation;
    #: windowed merges of these are the fleet latency decomposition
    PHASE_HISTOGRAMS = ("gen/e2e_s", "gen/phase/admit_wait_s",
                        "gen/phase/prefill_s", "gen/phase/decode_s",
                        "gen/phase/deliver_s")

    def ticks_observed(self) -> dict[str, int]:
        """Health ticks ingested per endpoint (windowed count). Cumulative
        histograms need TWO ticks to difference into a window delta, so
        an endpoint here with fewer than 2 cannot contribute to any
        windowed merge yet — the readiness signal
        :meth:`phase_percentiles` reports on an empty merge."""
        with self._lock:
            return {ep: len(s.ticks) for ep, s in self._series.items()}

    def phase_percentiles(self, ticks: int | None = None
                          ) -> dict[str, dict[str, float]]:
        """Fleet-merged per-phase latency percentiles over the last N
        ticks (default: slow window): the request ledger's phase
        histograms combined across every endpoint.  Phases nothing
        observed are omitted.  When NOTHING merged, returns the typed
        (and falsy — ``if pct:`` callers keep working)
        :class:`PhasesNotReady` instead of a bare ``{}``, carrying
        ``ticks_observed`` per endpoint: before an endpoint's second
        tick there is no delta to merge, and the caller can now tell
        "not ready yet" (some endpoint below 2 ticks) from "ledger off
        fleet-wide" (everyone ticking, still nothing) instead of
        guessing at an empty dict."""
        out: dict[str, dict[str, float]] = {}
        for name in self.PHASE_HISTOGRAMS:
            h = self.window_histogram(name, ticks or self.slow_ticks)
            if h is not None:
                out[name] = {k: h[k] for k in
                             ("count", "sum", "p50", "p95", "p99")}
        if not out:
            return PhasesNotReady(self.ticks_observed())
        return out

    def tenants(self) -> dict[str, dict[str, float]]:
        """Fleet-wide per-tenant consumption: every (endpoint, model)
        engine's latest ``tenants`` gauge block summed per tenant.  The
        gauges are cumulative over each engine's lifetime, so the sums
        are too — a replica restart zeroes that replica's contribution,
        like any gauge series."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for s in self._series.values():
                for g in s.gauges.values():
                    tens = g.get("tenants")
                    if not isinstance(tens, dict):
                        continue
                    for tenant, counters in tens.items():
                        if not isinstance(counters, dict):
                            continue
                        agg = out.setdefault(str(tenant), {})
                        for k, v in counters.items():
                            if isinstance(v, (int, float)):
                                agg[k] = agg.get(k, 0.0) + float(v)
        return out

    def fleet_goodput(self) -> dict[str, Any] | None:
        """Fleet goodput rollup: every (endpoint, model) engine's
        ``goodput`` gauge block merged by summing per-bucket seconds —
        equivalent to weighting each engine's fractions by the wall
        clock it accounted.  None when no engine reports one (ledger
        off fleet-wide)."""
        from paddle_tpu.serving.ledger import GOODPUT_USEFUL
        buckets: dict[str, float] = {}
        total = 0.0
        ticks = 0
        engines = 0
        with self._lock:
            for s in self._series.values():
                for g in s.gauges.values():
                    gp = g.get("goodput")
                    if not isinstance(gp, dict):
                        continue
                    engines += 1
                    total += float(gp.get("total_s", 0.0))
                    ticks += int(gp.get("ticks", 0))
                    for b, v in (gp.get("buckets") or {}).items():
                        if isinstance(v, (int, float)):
                            buckets[b] = buckets.get(b, 0.0) + float(v)
        if engines == 0:
            return None
        useful = sum(buckets.get(b, 0.0) for b in GOODPUT_USEFUL)
        return {
            "engines": engines, "total_s": total, "ticks": ticks,
            "buckets": buckets,
            "fractions": {b: (v / total if total > 0 else 0.0)
                          for b, v in buckets.items()},
            "goodput": useful / total if total > 0 else 0.0,
        }

    def fleet_kv(self) -> dict[str, Any] | None:
        """Fleet KV-store rollup: every (endpoint, model) engine's ``kv``
        gauge block (``serving/kvstore.py`` snapshot + engine counters)
        summed, with the derived fleet hit rate over all lookups — the
        disaggregated-serving scoreboard (`tools/perf_report.py`).  None
        when no engine reports one (store off fleet-wide)."""
        counters: dict[str, float] = {}
        roles: dict[str, int] = {}
        engines = 0
        degraded_engines = 0
        with self._lock:
            for s in self._series.values():
                for g in s.gauges.values():
                    kv = g.get("kv")
                    if not isinstance(kv, dict):
                        continue
                    engines += 1
                    if kv.get("degraded"):
                        degraded_engines += 1
                    role = kv.get("role")
                    if isinstance(role, str):
                        roles[role] = roles.get(role, 0) + 1
                    for k, v in kv.items():
                        if isinstance(v, (int, float)) and \
                                not isinstance(v, bool):
                            counters[k] = counters.get(k, 0.0) + float(v)
        if engines == 0:
            return None
        # kvstore counts spill_hits as a subset of hits (either tier)
        hits = counters.get("hits", 0.0)
        lookups = hits + counters.get("misses", 0.0)
        return {
            "engines": engines,
            "roles": roles,
            "counters": counters,
            "hit_rate": hits / lookups if lookups > 0 else 0.0,
            "fetch_bytes": counters.get("fetched_bytes", 0.0),
            "demotions": counters.get("demotions", 0.0),
            "prefill_recomputed": counters.get("prefill_recomputed", 0.0),
            # failure-domain visibility: stores reporting themselves
            # degraded (cordoned / breaker open), fetches that fell
            # back to recompute, deadline abandons, breaker trips
            "degraded_engines": degraded_engines,
            "fetch_degraded": counters.get("fetch_degraded", 0.0),
            "timeouts": counters.get("timeouts", 0.0),
            "breaker_opens": counters.get("breaker_opens", 0.0),
        }

    def fleet_emb(self) -> dict[str, Any] | None:
        """Fleet embedding-serving rollup (``FLAGS_serving_emb``): every
        replica's ``emb`` health block summed — cache hits/misses with
        the derived fleet hit rate, pulled rows/bytes, stale serves,
        rollovers — plus each served table's per-replica version spread
        (``versions``: table -> sorted unique versions; more than one
        entry means a rollover is still propagating).  None when no
        replica reports the tier (flag off fleet-wide)."""
        counters: dict[str, float] = {}
        versions: dict[str, set] = {}
        replicas = 0
        with self._lock:
            for s in self._series.values():
                emb = s.emb
                if not isinstance(emb, dict):
                    continue
                replicas += 1
                for k, v in emb.items():
                    if isinstance(v, (int, float)) and \
                            not isinstance(v, bool):
                        counters[k] = counters.get(k, 0.0) + float(v)
                tables = emb.get("tables")
                if isinstance(tables, dict):
                    for name, t in tables.items():
                        if isinstance(t, dict) and "version" in t:
                            versions.setdefault(str(name), set()).add(
                                int(t["version"]))
        if replicas == 0:
            return None
        hits = counters.get("hits", 0.0)
        lookups = hits + counters.get("misses", 0.0)
        return {
            "replicas": replicas,
            "counters": counters,
            "hit_rate": hits / lookups if lookups > 0 else 0.0,
            "pulled_rows": counters.get("pulled_rows", 0.0),
            "pulled_bytes": counters.get("pulled_bytes", 0.0),
            "stale_serves": counters.get("stale_serves", 0.0),
            "rollovers": counters.get("rollovers", 0.0),
            "versions": {n: sorted(vs) for n, vs in versions.items()},
        }

    def endpoints(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot(self) -> dict[str, Any]:
        """JSON-safe introspection doc (tests, chaos checks, dumps)."""
        with self._lock:
            return {
                "tick": self._tick,
                "fast_ticks": self.fast_ticks,
                "slow_ticks": self.slow_ticks,
                "endpoints": {
                    ep: {"last_tick": s.last_tick,
                         "ticks": len(s.ticks),
                         "models": sorted(s.gauges)}
                    for ep, s in self._series.items()},
            }
