"""Cross-request dynamic micro-batching for the inference server.

Reference role: what Paddle Serving's request scheduler does in front of
a predictor pool, in the Orca/Clipper shape: concurrent ``infer``
requests for the same model are queued, coalesced up to
``FLAGS_serving_batch_max`` total rows or ``FLAGS_serving_batch_timeout_s``
of waiting, run as ONE ``Predictor.run`` over the concatenated batch,
and split back per caller. On a TPU (and under XLA's per-call dispatch
overhead generally) one run of ``k`` rows costs far less than ``k`` runs
of one row — this is the serving-throughput lever the batch-frontier
numbers in ``BASELINE.md`` measure device-side, applied across the wire.

Mechanics:

- **Leader/follower coalescing.** Each request enqueues onto its model's
  queue; whichever handler thread finds no active leader becomes one,
  waits out the batching window (or until the row cap is hit), takes the
  FIFO prefix that fits, executes it, and distributes results. Followers
  just wait; leftover requests elect the next leader immediately.
- **Load watermark.** Coalescing taxes idle traffic: a lone request
  paid the full ``serving_batch_timeout_s`` window for a batch that was
  never coming (measured 0.57x vs unbatched at concurrency 1,
  BENCH_serving.json r5). A request that finds fewer than
  ``FLAGS_serving_batch_min_queue`` concurrent submits for its model —
  and no batch already forming — bypasses the queue and runs
  immediately (``serving/batch_bypass``); under real concurrency the
  watermark is crossed and coalescing engages as before. 0 restores
  unconditional coalescing.
- **Bucketed padding.** The concatenated batch is padded with zero rows
  up to the next power-of-two bucket (capped at ``serving_batch_max``),
  so the number of distinct shapes XLA compiles stays logarithmic in the
  cap. Padding rows are sliced away before replies; row-independent
  models (anything exported per-example) are unaffected by them.
- **Dynamic-batch artifacts only.** Coalescing needs a predictor whose
  batch axis is symbolic (``save_inference_model(...,
  dynamic_batch=True)``); fixed-shape models pass through unbatched.
- **Hard-off default.** With ``serving_batch_max`` at 0/1 (default) the
  server never constructs or consults the batcher — the serving path is
  byte-identical to the unbatched one (the ``FLAGS_trace`` pattern).

Observability: ``serving/batch_size`` + ``serving/batch_requests`` +
``serving/batch_wait_s`` histograms, ``serving/batches`` /
``serving/batched_requests`` / ``serving/batch_pad_rows`` counters, and
(when tracing) a ``serving/batch_wait`` span per request nested under
its wire server span, with the leader's ``serving/predict`` span showing
the shared execution — amortization reads directly off the timeline.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from paddle_tpu.core import fault as _fault
from paddle_tpu.core import trace as _trace
from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import observe, stat_add

__all__ = ["DynamicBatcher"]


def _bucket_rows(rows: int, max_rows: int) -> int:
    """Smallest power-of-two >= rows, capped at max_rows (oversized
    single requests run at their own size, unpadded)."""
    if rows >= max_rows:
        return rows
    b = 1
    while b < rows:
        b <<= 1
    return min(b, max_rows)


class _Pending:
    """One queued request: inputs in, outputs/error out."""

    __slots__ = ("inputs", "rows", "outputs", "error", "t0", "tenant")

    def __init__(self, inputs: list[np.ndarray], rows: int,
                 tenant: str | None = None):
        self.inputs = inputs
        self.rows = rows
        self.outputs: list[np.ndarray] | None = None
        self.error: BaseException | None = None
        self.t0 = time.perf_counter()
        self.tenant = tenant


class _ModelQueue:
    __slots__ = ("cv", "items", "leading", "inflight")

    def __init__(self):
        self.cv = threading.Condition()
        self.items: list[_Pending] = []
        self.leading = False
        self.inflight = 0     # concurrent submit() calls (load signal)


class DynamicBatcher:
    """Per-server coalescer of concurrent same-model infer requests.

    One instance per :class:`~paddle_tpu.io.serving.InferenceServer`
    (model names are only unique within a server). ``submit`` blocks the
    calling handler thread until its slice of a batch (or its solo run)
    completes, and raises whatever the combined execution raised.
    """

    def __init__(self, tenant_book=None):
        self._lock = threading.Lock()
        self._queues: dict[str, _ModelQueue] = {}
        # per-tenant infer attribution (serving/ledger.py TenantBook,
        # passed by the server when FLAGS_gen_ledger is on): a coalesced
        # run's wall clock splits evenly across its riders. None — the
        # default — books nothing and costs one is-None check per run.
        self._book = tenant_book
        # the replica's GenScheduler (FLAGS_gen_sched, installed by
        # InferenceServer.add_generator): consulted per submit for a
        # coalescing bypass while interactive SLO burn runs hot. None —
        # the default — costs one is-None check.
        self._sched = None

    def set_sched(self, sched) -> None:
        """Route this batcher's shed/bypass hints through the replica's
        generation scheduler (the one-shed-brain contract)."""
        self._sched = sched

    @staticmethod
    def can_batch(pred) -> bool:
        """Only dynamic-batch predictors participate; anything else
        (fixed-shape artifacts, duck-typed predictor objects) takes the
        ordinary unbatched path."""
        return bool(getattr(pred, "supports_batching", False))

    def submit(self, model: str, pred, inputs: list[np.ndarray],
               tenant: str | None = None) -> list[np.ndarray]:
        # Validate against the specs BEFORE enqueueing: a malformed
        # request must fail alone, never poison the batch it would have
        # ridden in (its peers' runs share one exported call).
        self._validate(pred, inputs)
        if not inputs:
            return self._run(pred, model, inputs, batched=False)
        rows = int(inputs[0].shape[0])
        q = self._queue(model)
        min_q = int(flag("serving_batch_min_queue"))
        with q.cv:
            q.inflight += 1
            # below the load watermark with no batch forming: skip the
            # coalescing window entirely — idle traffic must not pay the
            # timeout tax for a batch that is never coming
            solo = min_q > 0 and q.inflight < min_q and not q.items
        if (not solo and self._sched is not None
                and self._sched.infer_bypass(tenant)):
            # scheduler hint: interactive TTFT burn is hot — skip the
            # coalescing window so this request does not pay the
            # batching tax while latency budget is being spent
            solo = True
            stat_add("serving/batch_sched_bypass")
        try:
            if solo:
                stat_add("serving/batch_bypass")
                if self._book is None:
                    return self._run(pred, model, inputs, batched=False)
                t0 = time.perf_counter()
                outs = self._run(pred, model, inputs, batched=False)
                self._book.add(tenant, requests=1,
                               chip_s=time.perf_counter() - t0)
                return outs
            p = _Pending(inputs, rows, tenant)
            if _trace._ACTIVE is not None:
                with _trace.span("serving/batch_wait", model=model,
                                 rows=rows):
                    self._submit(q, pred, model, p)
            else:
                self._submit(q, pred, model, p)
        finally:
            with q.cv:
                q.inflight -= 1
        if p.error is not None:
            raise p.error
        assert p.outputs is not None
        return p.outputs

    # -- internals ---------------------------------------------------------
    @staticmethod
    def _validate(pred, inputs: list[np.ndarray]) -> None:
        specs = pred.input_specs
        if len(inputs) != len(specs):
            raise ValueError(
                f"expected {len(specs)} inputs, got {len(inputs)}")
        rows = None
        for i, (a, spec) in enumerate(zip(inputs, specs)):
            if len(a.shape) != len(spec["shape"]) or any(
                    e is not None and d != e
                    for d, e in zip(a.shape, spec["shape"])):
                raise ValueError(
                    f"input {i}: shape {list(a.shape)} != exported "
                    f"{spec['shape']}")
            if a.dtype.name != spec["dtype"]:
                raise ValueError(
                    f"input {i}: dtype {a.dtype} != exported "
                    f"{spec['dtype']}")
            if rows is None:
                rows = int(a.shape[0])
            elif int(a.shape[0]) != rows:
                raise ValueError(
                    "all inputs must share the batch-axis size; got "
                    f"{rows} vs {int(a.shape[0])} (input {i})")
            if rows == 0:
                raise ValueError("empty batch (0 rows)")

    def _queue(self, model: str) -> _ModelQueue:
        with self._lock:
            q = self._queues.get(model)
            if q is None:
                q = self._queues[model] = _ModelQueue()
            return q

    def pending(self, model: str) -> int:
        """Requests currently inside :meth:`submit` for ``model`` —
        queued on the batching window or executing. The ``unload_model``
        admin op consults this so an unload can fail clean (typed error)
        instead of yanking a predictor out from under a forming batch."""
        with self._lock:
            q = self._queues.get(model)
        if q is None:
            return 0
        with q.cv:
            return q.inflight

    def _submit(self, q: _ModelQueue, pred, model: str, p: _Pending
                ) -> None:
        with q.cv:
            q.items.append(p)
            q.cv.notify_all()        # a counting leader may now be full
            while p.outputs is None and p.error is None:
                if not q.leading:
                    q.leading = True
                    try:
                        self._lead(q, pred, model)
                    finally:
                        q.leading = False
                        q.cv.notify_all()
                else:
                    # followers poll with a bound: the post-execution
                    # notify_all normally wakes them immediately
                    q.cv.wait(0.05)

    def _lead(self, q: _ModelQueue, pred, model: str) -> None:
        """Called with ``q.cv`` held: wait out the batching window,
        take the FIFO prefix that fits, execute it outside the lock."""
        max_rows = max(int(flag("serving_batch_max")), 1)
        deadline = (time.perf_counter()
                    + float(flag("serving_batch_timeout_s")))
        while True:
            if sum(it.rows for it in q.items) >= max_rows:
                break
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            q.cv.wait(remaining)
        take: list[_Pending] = []
        total = 0
        for it in q.items:
            if take and total + it.rows > max_rows:
                break
            take.append(it)
            total += it.rows
        del q.items[:len(take)]
        q.cv.release()
        try:
            self._execute(pred, model, take, total, max_rows)
        finally:
            q.cv.acquire()

    def _execute(self, pred, model: str, take: list[_Pending],
                 total_rows: int, max_rows: int) -> None:
        t_exec = time.perf_counter()
        for it in take:
            observe("serving/batch_wait_s", t_exec - it.t0)
        try:
            # injection site for the whole coalesced execution: a flush
            # failure must fan out to every rider, never hang one
            _fault.inject("batcher.flush")
            if len(take) == 1:
                # solo flush: no concat/pad — identical to a direct run
                take[0].outputs = self._run(pred, model, take[0].inputs,
                                            batched=False)
            else:
                bucket = _bucket_rows(total_rows, max_rows)
                pad = bucket - total_rows
                cat = [
                    np.concatenate([it.inputs[i] for it in take], axis=0)
                    for i in range(len(take[0].inputs))]
                if pad:
                    cat = [np.concatenate(
                        [c, np.zeros((pad,) + c.shape[1:], c.dtype)],
                        axis=0) for c in cat]
                    stat_add("serving/batch_pad_rows", pad)
                outs = self._run(pred, model, cat, batched=True,
                                 requests=len(take))
                off = 0
                for it in take:
                    it.outputs = [np.asarray(o[off:off + it.rows])
                                  for o in outs]
                    off += it.rows
            stat_add("serving/batches")
            stat_add("serving/batched_requests", len(take))
            observe("serving/batch_size", total_rows)
            observe("serving/batch_requests", len(take))
            if self._book is not None:
                # one run served every rider: split its wall evenly
                share = (time.perf_counter() - t_exec) / len(take)
                for it in take:
                    self._book.add(it.tenant, requests=1, chip_s=share)
        except BaseException as e:  # every caller gets the failure
            for it in take:
                it.error = e

    @staticmethod
    def _run(pred, model: str, inputs, *, batched: bool,
             requests: int = 1) -> list[np.ndarray]:
        with _trace.span("serving/predict", model=model, batched=batched,
                         requests=requests):
            outs = pred.run(*inputs)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return [np.asarray(o) for o in outs]
