"""SLO-aware, tenant-fair generation scheduler (``FLAGS_gen_sched``,
hard-off).

One admission/preemption brain for the serving loop. Before this module,
scheduling policy was smeared across four places — FrameService's
inflight cap, the DynamicBatcher leader/follower, the GenerationEngine
slot loop (FIFO queue + ad-hoc knobs: page admission, prefill chunking,
spec shedding, KV-fetch budget), and the router. :class:`GenScheduler`
centralizes every per-iteration policy decision, in the Orca (OSDI '22)
iteration-level idiom the engine loop already follows mechanically:

- **Priority classes.** Requests carry ``interactive`` / ``batch`` /
  ``best_effort`` on the wire (header ``"pc"``, next to ``"tn"``);
  unclassed traffic is ``batch``. Interactive ranks strictly first for
  admission, gets shed headroom past the queue/inflight caps, and may
  preempt batch decode slots; best-effort is shed earliest and never
  preempts.
- **Weighted-fair queueing across tenants.** Start-time fair queueing
  (virtual-time tags) over the engine's wait queue: each (tenant,
  class) stream accrues virtual finish tags at a rate inversely
  proportional to its effective weight — class weight × tenant quota
  share, throttled when :class:`~paddle_tpu.serving.ledger.TenantBook`
  shows the tenant running over its chip-second share. Tags are
  assigned at enqueue and the queue is re-ordered (stable) each
  iteration, so a hot tenant cannot starve the others regardless of
  arrival order.
- **SLO-aware preemption.** When an interactive request is waiting and
  the engine has no free capacity, the scheduler picks victim slots
  (strictly lower class, most recently admitted first). The engine
  *parks* the victim by folding its emitted tokens into the prompt
  (the same prompt-replay + ``rng_skip`` contract the cross-replica
  resume path pins), releasing its slot/pages, and re-queueing it —
  resume is an ordinary re-admission whose chunked prefill recomputes
  the folded prefix, byte-identical for greedy and sampled streams.
- **Per-iteration budgets.** Each loop iteration asks
  :meth:`GenScheduler.plan` for an :class:`IterationPlan`: prefill
  chunk clamp, spec-k budget, KV-fetch admission scale, and a
  head-of-line bypass window — driven by whether interactive work is
  queued and by ``gen/ttft_s`` burn rates from an attached
  :class:`~paddle_tpu.serving.metrics.MetricsHub`.
- **One shed brain.** FrameService routes its would-shed decisions
  through :meth:`wire_gate` and the engine's ``start()`` through
  :meth:`shed_start`, so a request is never double-shed and class
  headroom is applied consistently; the DynamicBatcher consults
  :meth:`infer_bypass` to skip coalescing while interactive SLO burn
  runs hot.

Hard-off discipline: all flags are read here, at construction, once.
With ``gen_sched`` off the engine holds no scheduler and every hot-path
gate is a single ``is None`` attribute check — the default loop is
byte-identical (spy-pinned by ``tests/test_scheduler.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from paddle_tpu.core.flags import flag
from paddle_tpu.core.monitor import observe, stat_add

__all__ = ["GenScheduler", "IterationPlan", "INTERACTIVE", "BATCH",
           "BEST_EFFORT", "CLASSES", "classify"]

INTERACTIVE = "interactive"
BATCH = "batch"
BEST_EFFORT = "best_effort"
CLASSES = (INTERACTIVE, BATCH, BEST_EFFORT)

# admission rank: lower runs first; preemption is allowed only against
# strictly greater (worse) ranks
_RANK = {INTERACTIVE: 0, BATCH: 1, BEST_EFFORT: 2}

# accepted spellings of the "pc" wire header, normalized
_ALIASES = {
    "interactive": INTERACTIVE, "rt": INTERACTIVE, "realtime": INTERACTIVE,
    "0": INTERACTIVE,
    "batch": BATCH, "1": BATCH,
    "best_effort": BEST_EFFORT, "best-effort": BEST_EFFORT,
    "be": BEST_EFFORT, "2": BEST_EFFORT,
}

#: default TTFT SLO threshold (seconds) the burn-rate probe uses when
#: none is supplied to :meth:`GenScheduler.attach_hub`
DEFAULT_TTFT_SLO_S = 0.5
#: error budget (violating fraction) the burn-rate probe divides by
DEFAULT_TTFT_BUDGET = 0.1
#: recompute the (hub-backed) pressure signal at most every N plans —
#: keeps the per-iteration cost of an attached hub to a counter bump
_HUB_SAMPLE_EVERY = 64


def classify(priority: Any) -> str:
    """Map a wire ``"pc"`` header value to a priority class; anything
    unrecognized (including absent) is ``batch``."""
    if priority is None:
        return BATCH
    return _ALIASES.get(str(priority).strip().lower(), BATCH)


class IterationPlan:
    """What the scheduler decided for ONE engine-loop iteration.

    Every field has a "leave the engine's own policy alone" value so the
    loop applies the plan with cheap truthiness checks:

    - ``prefill_chunk``: clamp for this iteration's prefill chunk
      (tokens), or ``None`` to keep the engine's configured chunking.
    - ``spec_budget``: cap on speculative draft length this iteration
      (``0`` sheds speculation entirely), or ``None`` for the engine's
      own occupancy-based shedding.
    - ``kv_scale``: multiplier on the KV-fetch admission time budget
      (``1.0`` = unchanged; tightened under interactive pressure).
    - ``hol_window``: how many queue entries past a page-blocked head
      admission may scan for one that fits (head-of-line bypass);
      ``0`` keeps strict head-only admission.
    - ``preempt``: whether an interactive request is waiting and may
      claim a slot from a lower class this iteration.
    """

    __slots__ = ("prefill_chunk", "spec_budget", "kv_scale",
                 "hol_window", "preempt")

    def __init__(self, prefill_chunk: int | None = None,
                 spec_budget: int | None = None, kv_scale: float = 1.0,
                 hol_window: int = 0, preempt: bool = False):
        self.prefill_chunk = prefill_chunk
        self.spec_budget = spec_budget
        self.kv_scale = kv_scale
        self.hol_window = hol_window
        self.preempt = preempt


class GenScheduler:
    """The admission/preemption brain. One instance per engine; the
    serving layer shares it with FrameService and the DynamicBatcher so
    every shed/bypass decision flows through the same policy object.

    Thread-safety: the engine calls :meth:`plan` / :meth:`on_enqueue` /
    :meth:`note_admitted` under its own lock; the wire/batcher hooks
    (:meth:`wire_gate`, :meth:`infer_bypass`, :meth:`shed_start`) may
    race them, so all mutable scheduler state sits behind an internal
    lock of its own.
    """

    def __init__(self, tenant_book=None):
        self._lock = threading.Lock()
        self._w = {
            INTERACTIVE: max(float(flag("gen_sched_w_interactive")), 1e-6),
            BATCH: max(float(flag("gen_sched_w_batch")), 1e-6),
            BEST_EFFORT: max(float(flag("gen_sched_w_best_effort")), 1e-6),
        }
        self._quotas = self._parse_quotas(flag("gen_sched_quotas"))
        self._chunk = int(flag("gen_sched_chunk"))
        self._headroom = max(int(flag("gen_sched_headroom")), 0)
        self._book = tenant_book      # TenantBook (may be None)
        self._hub = None              # MetricsHub (attach_hub)
        self._slo_s = DEFAULT_TTFT_SLO_S
        self._slo_budget = DEFAULT_TTFT_BUDGET
        # start-time fair queueing state: global virtual time + the last
        # virtual finish tag per (tenant, class) backlog
        self._vt = 0.0
        self._tags: dict[tuple[str, str], float] = {}
        self._seq = 0
        # hub-pressure cache (recomputed every _HUB_SAMPLE_EVERY plans)
        self._plans = 0
        self._hot = False
        # policy counters (shipped in the engine's stats "sched" block)
        self._preemptions = 0
        self._quota_throttles = 0
        self._admitted = {c: 0 for c in CLASSES}
        self._sheds = {c: 0 for c in CLASSES}

    # -- construction-time wiring -----------------------------------------
    @staticmethod
    def _parse_quotas(spec: str) -> dict[str, float]:
        """``'alice=2,bob=1'`` → ``{'alice': 2.0, 'bob': 1.0}``; junk
        entries are dropped rather than raised (flags may come from
        operators' CLIs)."""
        out: dict[str, float] = {}
        for part in str(spec or "").split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            name, _, val = part.partition("=")
            try:
                share = float(val)
            except ValueError:
                continue
            if name.strip() and share > 0:
                out[name.strip()] = share
        return out

    def attach_hub(self, hub, slo_s: float | None = None,
                   budget: float | None = None) -> None:
        """Give the scheduler a MetricsHub to read ``gen/ttft_s`` burn
        rates from (fleet-wide and per-tenant)."""
        with self._lock:
            self._hub = hub
            if slo_s is not None:
                self._slo_s = float(slo_s)
            if budget is not None:
                self._slo_budget = float(budget)

    def attach_book(self, book) -> None:
        with self._lock:
            self._book = book

    def set_quotas(self, quotas) -> dict[str, float]:
        """Live quota reconfig (the controller's ``sched_quotas`` push):
        replace the tenant share map without a replica restart. Accepts
        a mapping or the flag's ``'alice=2,bob=1'`` string; non-positive
        shares are dropped (same hygiene as construction parsing).
        Returns the shares now in force."""
        if isinstance(quotas, str):
            q = self._parse_quotas(quotas)
        else:
            q = {}
            for name, share in (quotas or {}).items():
                try:
                    share = float(share)
                except (TypeError, ValueError):
                    continue
                if str(name).strip() and share > 0:
                    q[str(name).strip()] = share
        with self._lock:
            self._quotas = q
        stat_add("gen/sched/quota_reconfigs")
        return dict(q)

    # -- classification / fair-queue tagging -------------------------------
    classify = staticmethod(classify)

    def _weight(self, tenant: str | None, pclass: str) -> float:
        """Effective WFQ weight: class weight × tenant quota share,
        throttled (not zeroed) when the tenant is consuming chip-seconds
        beyond its share. Caller holds self._lock."""
        w = self._w[pclass] * self._quotas.get(tenant or "", 1.0)
        if self._book is not None and self._quotas:
            snap = self._book.snapshot()
            total = sum(t.get("chip_seconds", 0.0) for t in snap.values())
            mine = snap.get(tenant or "", {}).get("chip_seconds", 0.0)
            if total > 0.0 and mine > 0.0:
                qsum = sum(self._quotas.values()) or 1.0
                fair = self._quotas.get(tenant or "", 1.0) / qsum
                frac = mine / total
                if fair > 0.0 and frac > 2.0 * fair:
                    # running at >2x share: scale the weight down by the
                    # overuse ratio (bounded so the tenant is throttled,
                    # never starved)
                    w /= min(frac / fair, 8.0)
                    self._quota_throttles += 1
        return max(w, 1e-6)

    def on_enqueue(self, gen) -> None:
        """Assign the generation its priority rank + virtual finish tag
        at enqueue (and again on re-queue after a park — a parked stream
        re-enters the fair queue at current virtual time, so victims
        cannot be starved by a steady interactive trickle)."""
        with self._lock:
            self._seq += 1
            gen.sched_seq = self._seq
            cost = float(gen.prompt.size + gen.max_new_tokens)
            key = (gen.tenant or "", gen.pclass)
            start = max(self._vt, self._tags.get(key, 0.0))
            gen.sched_vft = start + cost / self._weight(gen.tenant,
                                                        gen.pclass)
            self._tags[key] = gen.sched_vft

    def order_key(self, gen):
        """Sort key for the engine's wait queue: class rank first
        (interactive strictly ahead), then virtual finish tag, then
        arrival order."""
        return (_RANK[gen.pclass], gen.sched_vft, gen.sched_seq)

    # -- per-iteration planning --------------------------------------------
    def _pressure(self) -> bool:
        """TTFT SLO pressure from the attached hub, sampled at most
        every ``_HUB_SAMPLE_EVERY`` plans. Caller holds self._lock."""
        self._plans += 1
        if self._hub is None:
            return False
        if self._plans % _HUB_SAMPLE_EVERY == 1:
            try:
                fast, _slow = self._hub.burn_rates(
                    "gen/ttft_s", self._slo_s, self._slo_budget)
                self._hot = fast > 1.0
            except Exception:
                self._hot = False
        return self._hot

    def plan(self, queue, slot_gen) -> IterationPlan:
        """Decide this iteration: re-order the wait queue (in place,
        stable) and return the iteration's budget plan. Called by the
        engine loop under the engine lock, once per iteration."""
        with self._lock:
            if len(queue) > 1:
                ordered = sorted(queue, key=self.order_key)
                queue.clear()
                queue.extend(ordered)
            head_interactive = bool(queue) and \
                queue[0].pclass == INTERACTIVE
            hot = self._pressure() or head_interactive
            free = sum(g is None for g in slot_gen)
            preempt = head_interactive and free == 0 and any(
                g is not None and _RANK[g.pclass] > _RANK[INTERACTIVE]
                for g in slot_gen)
        return IterationPlan(
            prefill_chunk=(self._chunk if hot and self._chunk > 0
                           else None),
            spec_budget=(0 if head_interactive else None),
            kv_scale=(0.5 if hot else 1.0),
            hol_window=8,
            preempt=preempt,
        )

    def choose_victims(self, candidates, pclass: str, need: int):
        """Pick up to ``need`` preemption victims for a waiting
        ``pclass`` stream from ``candidates`` — ``(slot, gen)`` pairs
        the ENGINE already screened for mechanical eligibility (decode
        phase, not mid-prefill). Policy here: strictly lower class
        only, most recently admitted first (least sunk work lost)."""
        rank = _RANK[pclass]
        eligible = [(s, g) for s, g in candidates
                    if _RANK[g.pclass] > rank]
        eligible.sort(key=lambda sg: -sg[1].sched_ts)
        return eligible[:max(int(need), 0)]

    # -- lifecycle notes (counters + fairness advancement) -----------------
    def note_admitted(self, gen, now: float | None = None) -> None:
        """A queued generation took a slot: advance virtual time to its
        start tag (SFQ service rule) and book its class queue-wait."""
        ts = time.monotonic() if now is None else float(now)
        with self._lock:
            gen.sched_ts = ts
            self._admitted[gen.pclass] += 1
            cost = float(gen.prompt.size + gen.max_new_tokens)
            self._vt = max(self._vt,
                           gen.sched_vft - cost / self._weight(
                               gen.tenant, gen.pclass))
        observe(f"gen/sched/wait_s/{gen.pclass}", max(ts - gen.created,
                                                      0.0))

    def note_parked(self, gen) -> None:
        with self._lock:
            self._preemptions += 1

    def note_shed(self, pclass: str) -> None:
        with self._lock:
            self._sheds[pclass] += 1

    # -- the one shed brain ------------------------------------------------
    def shed_start(self, pclass: str, pending: int,
                   queue_max: int) -> bool:
        """Engine ``start()`` admission: should this enqueue be shed?
        Class-aware caps around the engine's ``gen_queue_max``:
        interactive gets headroom past the cap, best-effort is shed at
        half of it. ``queue_max <= 0`` keeps the unlimited-queue
        semantics for every class."""
        if queue_max <= 0:
            return False
        rank = _RANK[pclass]
        if rank == 0:
            cap = queue_max + self._headroom
        elif rank == 2:
            cap = max(queue_max // 2, 1)
        else:
            cap = queue_max
        if pending >= cap:
            self.note_shed(pclass)
            return True
        return False

    def wire_gate(self, header, inflight: int, cap: int) -> bool:
        """FrameService consult on its WOULD-SHED path (inflight already
        at cap): return True to admit anyway. Only interactive traffic
        is let past the cap, and only within the configured headroom —
        the engine-side queue policy (same object) then decides its
        fate, so the request is never double-shed."""
        pclass = classify((header or {}).get("pc"))
        if pclass == INTERACTIVE and inflight < cap + self._headroom:
            return True
        self.note_shed(pclass)
        return False

    def infer_bypass(self, tenant: str | None = None) -> bool:
        """DynamicBatcher consult: skip the coalescing wait (leader
        dispatches solo) while interactive TTFT burn runs hot — trading
        batching efficiency for latency exactly when the SLO needs it."""
        with self._lock:
            if self._hub is None:
                return False
            try:
                fast, _slow = self._hub.burn_rates(
                    "gen/ttft_s", self._slo_s, self._slo_budget,
                    tenant=tenant)
                return fast > 1.0
            except Exception:
                return False

    # -- observability -----------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """The engine's ``stats()["sched"]`` block."""
        with self._lock:
            return {
                "preemptions": self._preemptions,
                "quota_throttles": self._quota_throttles,
                "admitted": dict(self._admitted),
                "sheds": dict(self._sheds),
                "weights": dict(self._w),
                "quotas": dict(self._quotas),
                "virtual_time": self._vt,
                "hot": self._hot,
            }
