"""paddle.jit equivalent — program capture and saved inference functions.

Reference: ``python/paddle/fluid/dygraph/jit.py`` (``@declarative`` /
``paddle.jit.to_static``: an AST transpiler rewriting imperative Python
into ProgramDesc graphs, ``dygraph_to_static/program_translator.py:729``)
plus ``paddle.jit.save/load`` (TranslatedLayer serialization).

On TPU the entire AST-transpiler layer is unnecessary: jax traces the
Python directly, so ``to_static`` IS ``jax.jit`` (with paddle's
``input_spec`` mapped to shape/dtype-declared example inputs) and
save/load ride the StableHLO export path (``paddle_tpu.io.export``).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.io.export import load_inference_model, save_inference_model

__all__ = ["to_static", "not_to_static", "save", "load", "InputSpec"]


class InputSpec:
    """Shape/dtype declaration (reference ``paddle.static.InputSpec``);
    None dims are unsupported under XLA's static-shape model — pad or
    bucket instead (the documented TPU recipe)."""

    def __init__(self, shape: Sequence[int], dtype="float32",
                 name: str | None = None):
        if any(d is None or (isinstance(d, int) and d < 0) for d in shape):
            raise ValueError(
                "dynamic dims are not supported on TPU (XLA compiles "
                "static shapes); bucket or pad the input instead")
        self.shape = tuple(int(d) for d in shape)
        self.dtype = jnp.dtype(dtype)
        self.name = name

    def example(self):
        return jnp.zeros(self.shape, self.dtype)


def to_static(function=None, *, input_spec: Sequence[InputSpec] | None = None,
              **jit_kwargs):
    """``@to_static`` — compile a Python callable.

    With ``input_spec``, the function is traced ahead of time against the
    declared shapes (the reference's eager program capture); without it,
    compilation happens at first call per shape signature, which is
    plain ``jax.jit`` behavior.
    """

    def wrap(fn):
        jitted = jax.jit(fn, **jit_kwargs)
        if input_spec:
            jitted.lower(*[s.example() for s in input_spec])
        return jitted

    return wrap(function) if function is not None else wrap


def not_to_static(fn):
    """Marker no-op (reference ``@not_to_static`` excludes a function from
    AST transpilation; with tracing there is nothing to exclude)."""
    return fn


def save(function, path: str, input_spec: Sequence[InputSpec]):
    """``paddle.jit.save``: serialize a traced function (StableHLO)."""
    save_inference_model(path, function,
                         [s.example() for s in input_spec],
                         forward=lambda f, *xs: f(*xs))


def load(path: str):
    """``paddle.jit.load``: a Predictor; call ``.run(*inputs)``."""
    return load_inference_model(path)
