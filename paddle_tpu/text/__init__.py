"""paddle_tpu.text — NLP datasets + vocab utilities.

Reference: ``python/paddle/text/`` (datasets: imdb, imikolov,
uci_housing, wmt14/16, movielens, conll05). Downloads are replaced by
local ``data_file`` paths (zero-egress) and a synthetic
``RandomTextDataset`` for smoke runs.
"""

from paddle_tpu.text.datasets import (
    Conll05st, Imdb, Imikolov, MovieLens, RandomTextDataset, UCIHousing,
    WMT14,
    WMT16,
)
from paddle_tpu.text.vocab import Vocab, simple_tokenize

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16", "MovieLens",
           "Conll05st", "RandomTextDataset", "Vocab", "simple_tokenize"]
