"""Vocabulary + tokenization utilities.

Reference: the word-dict builders embedded in each text dataset
(``python/paddle/text/datasets/imdb.py`` word_idx built from frequency
with a cutoff, ``imikolov.py`` build_dict with min_word_freq) — factored
here into one reusable ``Vocab`` so every dataset shares the same
encode/decode behavior.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable, Sequence

__all__ = ["Vocab", "simple_tokenize"]

_WORD_RE = re.compile(r"[A-Za-z0-9']+")


def simple_tokenize(text: str) -> list[str]:
    """Lowercase word tokenizer (the imdb ``tokenize`` analogue)."""
    return _WORD_RE.findall(text.lower())


class Vocab:
    def __init__(self, tokens: Sequence[str], *, unk_token: str | None = "<unk>",
                 pad_token: str | None = None, bos_token: str | None = None,
                 eos_token: str | None = None):
        specials = [t for t in (pad_token, unk_token, bos_token, eos_token)
                    if t is not None]
        self.itos: list[str] = list(dict.fromkeys(specials + list(tokens)))
        self.stoi: dict[str, int] = {t: i for i, t in enumerate(self.itos)}
        self.unk_token = unk_token
        self.pad_token = pad_token
        self.bos_token = bos_token
        self.eos_token = eos_token

    @classmethod
    def build(cls, corpus: Iterable[Sequence[str]], *, min_freq: int = 1,
              max_size: int | None = None, cutoff: int | None = None,
              **special_kw) -> "Vocab":
        """Frequency-sorted vocab. ``cutoff`` keeps tokens with freq >
        cutoff (imdb semantics); ``min_freq`` keeps freq >= min_freq
        (imikolov semantics)."""
        counter: Counter = Counter()
        for toks in corpus:
            counter.update(toks)
        if cutoff is not None:
            items = [(t, c) for t, c in counter.items() if c > cutoff]
        else:
            items = [(t, c) for t, c in counter.items() if c >= min_freq]
        # deterministic: by (-freq, token), the reference's sort order
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        if max_size is not None:
            items = items[:max_size]
        return cls([t for t, _ in items], **special_kw)

    def __len__(self) -> int:
        return len(self.itos)

    def __contains__(self, token: str) -> bool:
        return token in self.stoi

    def __getitem__(self, token: str) -> int:
        idx = self.stoi.get(token)
        if idx is None:
            if self.unk_token is None:
                raise KeyError(token)
            return self.stoi[self.unk_token]
        return idx

    def encode(self, tokens: Sequence[str], *, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        out = []
        if add_bos:
            out.append(self.stoi[self.bos_token])
        out.extend(self[t] for t in tokens)
        if add_eos:
            out.append(self.stoi[self.eos_token])
        return out

    def decode(self, ids: Sequence[int]) -> list[str]:
        return [self.itos[i] for i in ids]
