"""Text/NLP datasets (the ``paddle.text.datasets`` surface).

Reference: ``python/paddle/text/datasets/`` — imdb, imikolov,
uci_housing, wmt14/wmt16, movielens, conll05. Same formats and field
semantics; the downloaders are gone (zero-egress environment — every
dataset takes a local ``data_file``), and ``RandomTextDataset`` covers
smoke-training the way ``vision.RandomImageDataset`` does for images.
"""

from __future__ import annotations

import io
import os
import re
import tarfile
from collections import Counter

import numpy as np

from paddle_tpu.data.dataset import Dataset
from paddle_tpu.text.vocab import Vocab, simple_tokenize

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16", "MovieLens",
           "Conll05st", "RandomTextDataset"]


def _require_file(path, name):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{name} needs a local data_file (no download in this "
            f"zero-egress environment); got {path!r}")


class Imdb(Dataset):
    """IMDB sentiment (reference ``text/datasets/imdb.py``): aclImdb tar
    with ``{train,test}/{pos,neg}/*.txt`` docs; word dict built from the
    train split with a frequency ``cutoff``; samples are (ids, label)."""

    def __init__(self, data_file: str, mode: str = "train",
                 cutoff: int = 150):
        _require_file(data_file, "Imdb")
        if mode not in ("train", "test"):
            raise ValueError(f"mode {mode!r}")
        self.mode = mode
        docs_by_split: dict[str, list[tuple[list[str], int]]] = {
            "train": [], "test": []}
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", errors="ignore")
                label = 0 if m.group(2) == "pos" else 1  # reference: pos=0
                docs_by_split[m.group(1)].append(
                    (simple_tokenize(text), label))
        # dict always from train (reference builds word_idx on train files)
        self.word_idx = Vocab.build(
            (toks for toks, _ in docs_by_split["train"]), cutoff=cutoff,
            unk_token="<unk>")
        self.docs = [np.array(self.word_idx.encode(toks), np.int64)
                     for toks, _ in docs_by_split[mode]]
        self.labels = np.array([lab for _, lab in docs_by_split[mode]],
                               np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language modeling (reference ``imikolov.py``): NGRAM mode
    yields window_size-grams, SEQ mode yields (src, trg) shifted
    sequences with <s>/<e> markers."""

    def __init__(self, data_file: str, mode: str = "train",
                 data_type: str = "NGRAM", window_size: int = -1,
                 min_word_freq: int = 50):
        _require_file(data_file, "Imikolov")
        if data_type == "NGRAM" and window_size < 2:
            raise ValueError("NGRAM mode needs window_size >= 2")
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]
        lines_by_file: dict[str, list[list[str]]] = {}
        if tarfile.is_tarfile(data_file):
            with tarfile.open(data_file) as tf:
                for member in tf.getmembers():
                    base = os.path.basename(member.name)
                    if base in ("ptb.train.txt", "ptb.valid.txt"):
                        raw = tf.extractfile(member).read().decode()
                        lines_by_file[base] = [ln.split() for ln in
                                               raw.splitlines() if ln.strip()]
        else:
            with open(data_file) as f:
                lines_by_file[name] = [ln.split() for ln in f
                                       if ln.strip()]
        train_lines = lines_by_file.get("ptb.train.txt",
                                        lines_by_file.get(name, []))
        self.word_idx = Vocab.build(train_lines, min_freq=min_word_freq,
                                    unk_token="<unk>", bos_token="<s>",
                                    eos_token="<e>")
        self.data = []
        for toks in lines_by_file.get(name, []):
            ids = self.word_idx.encode(toks, add_bos=True, add_eos=True)
            if data_type == "NGRAM":
                for i in range(window_size, len(ids) + 1):
                    self.data.append(np.array(ids[i - window_size:i],
                                              np.int64))
            elif data_type == "SEQ":
                self.data.append((np.array(ids[:-1], np.int64),
                                  np.array(ids[1:], np.int64)))
            else:
                raise ValueError(f"data_type {data_type!r}")

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (reference ``uci_housing.py``): 14
    whitespace-separated columns; features normalized by
    (x - mean) / (max - min); 80/20 train/test split."""

    FEATURE_NUM = 14

    def __init__(self, data_file: str, mode: str = "train"):
        _require_file(data_file, "UCIHousing")
        data = np.fromfile(data_file, sep=" ")
        data = data.reshape(data.shape[0] // self.FEATURE_NUM,
                            self.FEATURE_NUM)
        maxi, mini = data.max(axis=0), data.min(axis=0)
        avgs = data.mean(axis=0)
        for i in range(self.FEATURE_NUM - 1):
            rng = maxi[i] - mini[i]
            data[:, i] = (data[:, i] - avgs[i]) / (rng if rng else 1.0)
        offset = int(data.shape[0] * 0.8)
        self.data = data[:offset] if mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype(np.float32),
                row[-1:].astype(np.float32))

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """EN↔FR translation (reference ``wmt14.py``): parallel ``.src`` /
    ``.trg`` token files + ``.dict`` vocabularies inside a tar; samples
    are (src_ids, trg_ids_with_bos, trg_ids_with_eos)."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file: str, mode: str = "train",
                 dict_size: int = 30000):
        _require_file(data_file, "WMT14")
        src_lines, trg_lines = [], []
        src_dict = trg_dict = None
        want = {"train": "train", "test": "test", "gen": "gen"}[mode]
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                read = lambda: tf.extractfile(member).read().decode()
                if base == f"{want}.src":
                    src_lines = [ln.split() for ln in read().splitlines()]
                elif base == f"{want}.trg":
                    trg_lines = [ln.split() for ln in read().splitlines()]
                elif base == "src.dict":
                    src_dict = read().split()[:dict_size]
                elif base == "trg.dict":
                    trg_dict = read().split()[:dict_size]
        if src_dict is None or trg_dict is None:
            # dicts built from the data when the tar ships none
            src_dict = sorted({t for ln in src_lines for t in ln})
            trg_dict = sorted({t for ln in trg_lines for t in ln})
        self.src_vocab = Vocab(src_dict, unk_token=self.UNK,
                               bos_token=self.BOS, eos_token=self.EOS)
        self.trg_vocab = Vocab(trg_dict, unk_token=self.UNK,
                               bos_token=self.BOS, eos_token=self.EOS)
        self.data = []
        for s, t in zip(src_lines, trg_lines):
            sid = np.array(self.src_vocab.encode(s), np.int64)
            tid = self.trg_vocab.encode(t)
            bos = self.trg_vocab.stoi[self.BOS]
            eos = self.trg_vocab.stoi[self.EOS]
            self.data.append((sid, np.array([bos] + tid, np.int64),
                              np.array(tid + [eos], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT16(Dataset):
    """EN↔DE translation (reference ``wmt16.py``): the tar holds
    tab-separated parallel lines at ``wmt16/{train,test,val}`` and
    optional frequency-sorted dictionaries at ``wmt16/{en,de}.dict``
    (built from the training split when absent, reference
    ``_build_dict``). Samples are (src_ids with bos/eos,
    trg_ids_with_bos, trg_ids_with_eos); ``lang`` picks which column is
    the source ("en" → en→de)."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file: str, mode: str = "train",
                 src_dict_size: int = 30000, trg_dict_size: int = 30000,
                 lang: str = "en"):
        if mode not in ("train", "test", "val"):
            raise ValueError(f"mode should be train/test/val, got {mode!r}")
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang!r}")
        _require_file(data_file, "WMT16")
        src_col = 0 if lang == "en" else 1
        pairs: list[tuple[list[str], list[str]]] = []
        train_pairs: list[tuple[list[str], list[str]]] = []
        dicts: dict[str, list[str]] = {}
        with tarfile.open(data_file) as tf:
            members = {os.path.basename(m.name): m
                       for m in tf.getmembers()
                       if os.path.basename(m.name) in
                       ("train", "test", "val", "en.dict", "de.dict")}
            for key in ("en.dict", "de.dict"):
                if key in members:
                    dicts[key[:-5]] = (
                        tf.extractfile(members[key]).read().decode()
                        .split())

            def parse(name):
                rows = []
                text = tf.extractfile(members[name]).read().decode()
                for line in text.splitlines():
                    cols = line.strip().split("\t")
                    if len(cols) == 2:
                        rows.append((cols[src_col].split(),
                                     cols[1 - src_col].split()))
                return rows

            if mode in members:
                pairs = parse(mode)
            if mode == "train":
                train_pairs = pairs
            elif (("en" not in dicts or "de" not in dicts)
                    and "train" in members):
                # only pay for tokenizing the (large) train split when a
                # vocabulary actually has to be built from it
                train_pairs = parse("train")

        def vocab_for(key, col, size):
            if key in dicts:
                tokens = dicts[key][:size]
            else:
                # reference _build_dict: frequency-sorted from train
                return Vocab.build(
                    (p[col] for p in (train_pairs or pairs)),
                    max_size=size, unk_token=self.UNK,
                    bos_token=self.BOS, eos_token=self.EOS)
            return Vocab(tokens, unk_token=self.UNK, bos_token=self.BOS,
                         eos_token=self.EOS)

        self._lang = lang
        src_key = lang
        trg_key = "de" if lang == "en" else "en"
        self.src_vocab = vocab_for(src_key, 0, src_dict_size)
        self.trg_vocab = vocab_for(trg_key, 1, trg_dict_size)
        bos = self.trg_vocab.stoi[self.BOS]
        eos = self.trg_vocab.stoi[self.EOS]
        sbos = self.src_vocab.stoi[self.BOS]
        seos = self.src_vocab.stoi[self.EOS]
        self.data = []
        for s, t in pairs:
            # reference wraps the SOURCE in <s>…<e> too (wmt16.py
            # _load_data), unlike wmt14
            sid = np.array([sbos] + self.src_vocab.encode(s) + [seos],
                           np.int64)
            tid = self.trg_vocab.encode(t)
            self.data.append((sid, np.array([bos] + tid, np.int64),
                              np.array(tid + [eos], np.int64)))

    def get_dict(self, lang: str, reverse: bool = False):
        """Word dict for a language (reference API). ``reverse`` →
        id→word."""
        vocab = self.src_vocab if lang == getattr(self, "_lang", "en") \
            else self.trg_vocab
        if reverse:
            return dict(enumerate(vocab.itos))
        return dict(vocab.stoi)

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class MovieLens(Dataset):
    """MovieLens-1M ratings (reference ``movielens.py``): ``::``-separated
    movies/users/ratings files in a directory or tar; samples are
    (user_id, gender, age, job, movie_id, category_ids, title_ids,
    rating)."""

    def __init__(self, data_file: str, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        _require_file(data_file, "MovieLens")
        raw = {}
        names = ("movies.dat", "users.dat", "ratings.dat")
        if os.path.isdir(data_file):
            for n in names:
                with open(os.path.join(data_file, n), encoding="latin1") as f:
                    raw[n] = f.read()
        else:
            with tarfile.open(data_file) as tf:
                for member in tf.getmembers():
                    base = os.path.basename(member.name)
                    if base in names:
                        raw[base] = tf.extractfile(member).read().decode(
                            "latin1")

        cat_vocab: dict[str, int] = {}
        title_vocab: dict[str, int] = {}
        self.movies = {}
        for line in raw["movies.dat"].splitlines():
            if not line.strip():
                continue
            mid, title, cats = line.strip().split("::")
            cat_ids = [cat_vocab.setdefault(c, len(cat_vocab))
                       for c in cats.split("|")]
            tit_ids = [title_vocab.setdefault(w, len(title_vocab))
                       for w in simple_tokenize(title)]
            self.movies[int(mid)] = (np.array(cat_ids, np.int64),
                                     np.array(tit_ids, np.int64))
        self.users = {}
        for line in raw["users.dat"].splitlines():
            if not line.strip():
                continue
            uid, gender, age, job, _ = line.strip().split("::")
            self.users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                    int(job))
        ratings = []
        for line in raw["ratings.dat"].splitlines():
            if not line.strip():
                continue
            uid, mid, rating, _ = line.strip().split("::")
            ratings.append((int(uid), int(mid), float(rating)))
        rs = np.random.RandomState(rand_seed)
        test_mask = rs.rand(len(ratings)) < test_ratio
        self.data = [r for r, t in zip(ratings, test_mask)
                     if (mode == "test") == bool(t)]

    def __getitem__(self, idx):
        uid, mid, rating = self.data[idx]
        gender, age, job = self.users[uid]
        cats, title = self.movies[mid]
        return (np.int64(uid), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mid), cats, title,
                np.float32(rating))

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference ``conll05.py``): parallel words/props
    files; each sample is (word_ids, predicate_id, label_ids) — the
    sequence-labeling fields the reference emits, minus the 5 context
    windows (derivable from word_ids; the reference precomputes them for
    its fixed LSTM-SRL demo)."""

    def __init__(self, words_file: str, props_file: str,
                 word_vocab: Vocab | None = None,
                 label_vocab: Vocab | None = None):
        _require_file(words_file, "Conll05st")
        _require_file(props_file, "Conll05st")
        sentences = self._read_blocks(words_file)
        props = self._read_blocks(props_file)
        samples = []
        for sent, prop in zip(sentences, props):
            words = [cols[0] for cols in sent]
            preds = [cols[0] for cols in prop]
            n_frames = len(prop[0]) - 1
            for f in range(n_frames):
                tags = self._spans_to_iob([cols[1 + f] for cols in prop])
                pred_idx = next(i for i, p in enumerate(preds)
                                if p != "-" and tags[i].endswith("-V"))
                samples.append((words, pred_idx, tags))
        self.word_vocab = word_vocab or Vocab.build(
            (w for w, _, _ in samples), unk_token="<unk>")
        self.label_vocab = label_vocab or Vocab.build(
            (t for _, _, t in samples), unk_token=None)
        self.data = [
            (np.array(self.word_vocab.encode(w), np.int64),
             np.int64(p),
             np.array([self.label_vocab[t] for t in tags], np.int64))
            for w, p, tags in samples]

    @staticmethod
    def _read_blocks(path):
        blocks, cur = [], []
        with open(path) as f:
            for line in f:
                if line.strip():
                    cur.append(line.split())
                elif cur:
                    blocks.append(cur)
                    cur = []
        if cur:
            blocks.append(cur)
        return blocks

    @staticmethod
    def _spans_to_iob(col):
        """CoNLL prop spans '(A0*' '*' '*)' → IOB-ish tags."""
        tags, current = [], None
        for tok in col:
            m = re.match(r"\(([^*()]+)", tok)
            if m:
                current = m.group(1)
                tags.append(f"B-{current}")
            elif current is not None:
                tags.append(f"I-{current}")
            else:
                tags.append("O")
            if ")" in tok:
                current = None
        return tags

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class RandomTextDataset(Dataset):
    """Deterministic synthetic token sequences for tests/smoke LM
    training (the text counterpart of vision.RandomImageDataset)."""

    def __init__(self, num_samples: int = 256, seq_len: int = 64,
                 vocab_size: int = 1000, seed: int = 0):
        rs = np.random.RandomState(seed)
        self.ids = rs.randint(0, vocab_size, (num_samples, seq_len)).astype(
            np.int64)
        self.vocab_size = vocab_size

    def __getitem__(self, idx):
        return self.ids[idx]

    def __len__(self):
        return len(self.ids)
