"""paddle.tensor API surface — paddle calling conventions over jax.numpy.

Reference: ``python/paddle/tensor/`` (manipulation.py, math.py, linalg.py,
search.py, logic.py, stat.py — ~8.6k LoC of Python dispatching to the
C++ op library). On TPU these are jnp/lax one-liners; what this module
adds is the *paddle semantics* where they differ from numpy:
``split(num_or_sections)``, ``topk``/``sort`` return conventions,
``gather`` defaulting to axis 0, ``scatter`` overwrite-vs-add,
``norm``'s fro default, ``unique``'s optional index/counts outputs, etc.

Everything here is jit-compatible except the documented exceptions
(``nonzero``/``masked_select`` produce data-dependent shapes — eager
only, same caveat the reference's dynamic-shape ops carry on XLA).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    # manipulation
    "concat", "split", "chunk", "stack", "unstack", "unbind", "squeeze",
    "unsqueeze", "reshape", "flatten", "transpose", "t", "flip", "roll",
    "tile", "expand", "expand_as", "broadcast_to", "gather", "gather_nd",
    "scatter", "scatter_nd_add", "index_select", "index_sample",
    "masked_select", "unique", "shard_index",
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "pow",
    "sqrt", "rsqrt", "exp", "log", "log2", "log10", "log1p", "abs", "ceil",
    "floor", "round", "sign", "reciprocal", "square", "maximum", "minimum",
    "sum", "mean", "max", "min", "prod", "cumsum", "cumprod", "logsumexp",
    "argmax", "argmin", "addmm", "matmul", "dot", "bmm", "mv", "kron",
    "trace", "multiply", "erf", "isnan", "isinf", "isfinite", "clip",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "atan2",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "allclose", "equal_all", "is_empty",
    # linalg
    "norm", "dist", "cross", "cholesky", "histogram", "tril", "triu",
    "diag", "meshgrid",
    # search / sort
    "argsort", "sort", "topk", "where", "nonzero",
    # stat
    "std", "var", "median", "numel",
]


# ---------------------------------------------------------------------------
# manipulation (reference python/paddle/tensor/manipulation.py)
# ---------------------------------------------------------------------------

def concat(x, axis: int = 0):
    return jnp.concatenate(x, axis=axis)


def split(x, num_or_sections, axis: int = 0):
    """paddle semantics: int → equal parts; list → section sizes (a -1
    entry infers its size)."""
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    import builtins

    sections = list(num_or_sections)
    if -1 in sections:
        known = builtins.sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return jnp.split(x, idx, axis=axis)


def chunk(x, chunks: int, axis: int = 0):
    return jnp.split(x, chunks, axis=axis)


def stack(x, axis: int = 0):
    return jnp.stack(x, axis=axis)


def unstack(x, axis: int = 0):
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, x.shape[axis], axis=axis)]


unbind = unstack


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


def reshape(x, shape):
    return jnp.reshape(x, shape)


def flatten(x, start_axis: int = 0, stop_axis: int = -1):
    stop = stop_axis if stop_axis >= 0 else x.ndim + stop_axis
    return x.reshape(x.shape[:start_axis] + (-1,) + x.shape[stop + 1:])


def transpose(x, perm):
    return jnp.transpose(x, perm)


def t(x):
    return x.T


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def expand(x, shape):
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


broadcast_to = expand


def gather(x, index, axis: int = 0):
    """Row gather along ``axis`` (reference ``gather_op``; axis default 0
    unlike numpy.take's flattened default)."""
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    """Gather by coordinate tuples in the trailing dim of ``index``."""
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def scatter(x, index, updates, overwrite: bool = True):
    """Row scatter into axis 0 (reference ``scatter_op``): overwrite or
    accumulate."""
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def index_select(x, index, axis: int = 0):
    return jnp.take(x, index, axis=axis)


def index_sample(x, index):
    """Per-row column sampling: out[i, j] = x[i, index[i, j]]
    (reference ``index_sample_op``)."""
    return jnp.take_along_axis(x, index, axis=1)


def masked_select(x, mask):
    """Data-dependent output shape → eager only (same XLA caveat as the
    reference's dynamic-shape path)."""
    import numpy as np

    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def unique(x, return_index: bool = False, return_inverse: bool = False,
           return_counts: bool = False):
    import numpy as np

    out = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts)
    if isinstance(out, tuple):
        return tuple(jnp.asarray(o) for o in out)
    return jnp.asarray(out)


def shard_index(x, index_num: int, nshards: int, shard_id: int,
                ignore_value: int = -1):
    """Map global ids to shard-local ids (reference ``shard_index_op``,
    the PS sparse-table row router)."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


# ---------------------------------------------------------------------------
# math (reference python/paddle/tensor/math.py)
# ---------------------------------------------------------------------------

def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def mod(x, y):
    return jnp.mod(x, y)


def pow(x, y):
    return jnp.power(x, y)


for _name in ("sqrt", "exp", "log", "log2", "log10", "log1p", "abs",
              "ceil", "floor", "sign", "square", "sin", "cos", "tan",
              "asin", "acos", "atan", "sinh", "cosh", "tanh", "isnan",
              "isinf", "isfinite", "cumsum", "cumprod", "atan2"):
    globals()[_name] = getattr(jnp, _name)


def rsqrt(x):
    return lax.rsqrt(x)


def round(x):
    return jnp.round(x)


def reciprocal(x):
    return 1.0 / x


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def sum(x, axis=None, keepdim: bool = False):
    return jnp.sum(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim: bool = False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim: bool = False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim: bool = False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim: bool = False):
    return jnp.prod(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim: bool = False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def argmax(x, axis=None, keepdim: bool = False):
    out = jnp.argmax(x, axis=axis)
    return jnp.expand_dims(out, axis) if keepdim and axis is not None else out


def argmin(x, axis=None, keepdim: bool = False):
    out = jnp.argmin(x, axis=axis)
    return jnp.expand_dims(out, axis) if keepdim and axis is not None else out


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0):
    return beta * input + alpha * (x @ y)


def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def bmm(x, y):
    return jnp.matmul(x, y)


def mv(x, vec):
    return x @ vec


def kron(x, y):
    return jnp.kron(x, y)


def trace(x, offset: int = 0, axis1: int = 0, axis2: int = 1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def erf(x):
    return jax.scipy.special.erf(x)


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


# ---------------------------------------------------------------------------
# logic (reference python/paddle/tensor/logic.py)
# ---------------------------------------------------------------------------

def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def logical_not(x):
    return jnp.logical_not(x)


def allclose(x, y, rtol: float = 1e-5, atol: float = 1e-8,
             equal_nan: bool = False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def is_empty(x):
    return x.size == 0


# ---------------------------------------------------------------------------
# linalg (reference python/paddle/tensor/linalg.py)
# ---------------------------------------------------------------------------

def norm(x, p="fro", axis=None, keepdim: bool = False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def dist(x, y, p: float = 2.0):
    return jnp.linalg.norm((x - y).reshape(-1), ord=p)


def cross(x, y, axis: int = -1):
    return jnp.cross(x, y, axis=axis)


def cholesky(x, upper: bool = False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


def histogram(x, bins: int = 100, min=0, max=0):
    if min == 0 and max == 0:
        min, max = float(jnp.min(x)), float(jnp.max(x))
    hist, _ = jnp.histogram(x, bins=bins, range=(min, max))
    return hist


def tril(x, diagonal: int = 0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal: int = 0):
    return jnp.triu(x, k=diagonal)


def diag(x, offset: int = 0):
    return jnp.diag(x, k=offset)


def meshgrid(*args):
    return jnp.meshgrid(*args, indexing="ij")


# ---------------------------------------------------------------------------
# search / sort (reference python/paddle/tensor/search.py)
# ---------------------------------------------------------------------------

def argsort(x, axis: int = -1, descending: bool = False):
    idx = jnp.argsort(x, axis=axis)
    return jnp.flip(idx, axis=axis) if descending else idx


def sort(x, axis: int = -1, descending: bool = False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def topk(x, k: int, axis: int = -1, largest: bool = True,
         sorted: bool = True):
    """(values, indices), paddle convention."""
    del sorted
    if axis in (-1, x.ndim - 1):
        if largest:
            return lax.top_k(x, k)
        vals, idx = lax.top_k(-x, k)
        return -vals, idx
    x_m = jnp.moveaxis(x, axis, -1)
    vals, idx = topk(x_m, k, -1, largest)
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple: bool = False):
    """Eager only (data-dependent shape)."""
    import numpy as np

    out = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(o) for o in out)
    return jnp.asarray(np.stack(out, axis=1))


# ---------------------------------------------------------------------------
# stat (reference python/paddle/tensor/stat.py)
# ---------------------------------------------------------------------------

def std(x, axis=None, unbiased: bool = True, keepdim: bool = False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def var(x, axis=None, unbiased: bool = True, keepdim: bool = False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def median(x, axis=None, keepdim: bool = False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def numel(x):
    return x.size
