"""paddle_tpu.quant — QAT + PTQ (the slim/quantization equivalent).

Reference: ``python/paddle/fluid/contrib/slim/quantization/`` —
QuantizationTransformPass (QAT fake-quant insertion),
PostTrainingQuantization (calibration), QuantizationFreezePass (int8
freeze). See ``qat.py`` / ``ptq.py`` for the TPU-native mapping (module
surgery instead of program rewriting; real int8 MXU matmuls after
freeze).
"""

from paddle_tpu.quant import functional
from paddle_tpu.quant.functional import (
    fake_channel_wise_quant_abs_max, fake_quant, fake_quant_abs_max,
    moving_average_abs_max_scale, quant_max,
)
from paddle_tpu.quant.qat import (
    QuantConfig, QuantedConv2D, QuantedLinear, quantize_model,
)
from paddle_tpu.quant.ptq import (
    Int8Linear, calibrate, convert_to_int8, int8_state_dict,
)
from paddle_tpu.quant.weight_only import (
    WeightOnlyInt8Linear, quantize_weights_int8,
)

__all__ = ["functional", "fake_quant", "fake_quant_abs_max",
           "fake_channel_wise_quant_abs_max", "moving_average_abs_max_scale",
           "quant_max", "QuantConfig", "QuantedLinear", "QuantedConv2D",
           "quantize_model", "calibrate", "convert_to_int8", "Int8Linear",
           "int8_state_dict", "WeightOnlyInt8Linear",
           "quantize_weights_int8"]
