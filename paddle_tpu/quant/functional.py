"""Fake-quantization primitives with straight-through gradients.

Reference: ``paddle/fluid/operators/fake_quantize_op.cc`` /
``fake_quantize_op.cu`` (fake_quantize_abs_max,
fake_channel_wise_quantize_abs_max, fake_quantize_moving_average_abs_max —
the op set the slim QAT passes insert,
``fluid/contrib/slim/quantization/quantization_pass.py``).

TPU notes: the quant-dequant round trips stay in fp32/bf16 (XLA fuses
them into the surrounding ops), and gradients use the straight-through
estimator via ``jax.custom_vjp`` — pass-through inside the clip range,
zero outside, matching the reference's FakeQuantGradFunctor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["quant_max", "fake_quant", "fake_quant_abs_max",
           "fake_channel_wise_quant_abs_max",
           "moving_average_abs_max_scale"]


def quant_max(bits: int = 8) -> float:
    return float(2 ** (bits - 1) - 1)


@jax.custom_vjp
def _fake_quant_ste(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant_ste(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    s = jnp.maximum(scale, 1e-8)
    # STE: identity inside [-scale, scale], zero outside (clipped region)
    mask = (jnp.abs(x) <= s).astype(g.dtype)
    return g * mask, None, None


_fake_quant_ste.defvjp(_fq_fwd, _fq_bwd)


def fake_quant(x, scale, bits: int = 8):
    """Quantize-dequantize against a given scale (broadcastable)."""
    return _fake_quant_ste(x, scale, quant_max(bits))


def fake_quant_abs_max(x, bits: int = 8):
    """Dynamic per-tensor abs-max fake quant (fake_quantize_abs_max).
    Returns (quantized, scale); scale carries no gradient."""
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    return fake_quant(x, scale, bits), scale


def fake_channel_wise_quant_abs_max(w, bits: int = 8, axis: int = 0):
    """Per-output-channel abs-max fake quant
    (fake_channel_wise_quantize_abs_max; the reference quantizes conv
    weights along the output-channel axis)."""
    red = tuple(i for i in range(w.ndim) if i != axis)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(w), axis=red,
                                          keepdims=True))
    return fake_quant(w, scale, bits), jnp.squeeze(scale)


def moving_average_abs_max_scale(x, running_scale, momentum: float = 0.9):
    """EMA of the activation abs-max
    (fake_quantize_moving_average_abs_max's state update); returns the new
    running scale (stop-grad)."""
    now = jnp.max(jnp.abs(jax.lax.stop_gradient(x)))
    return momentum * running_scale + (1.0 - momentum) * now


def channelwise_int8_freeze(w, *, axis: int = -2, qmax: int = 127,
                            scale_dtype=None):
    """Symmetric per-channel int8 freeze: returns ``(wq int8, scale)``
    with ``dequant = wq * scale`` and ``scale = absmax/qmax`` reduced
    over ``axis`` (every axis except the channel axes). The elementwise
    error is bounded by ``scale/2``.

    ``scale_dtype`` rounds the scale to a storage dtype BEFORE
    quantizing, so dequant with the stored (e.g. bf16) scale stays on
    the freeze grid and the error bound still holds.

    This is the same quantization grid ``ptq.convert_to_int8`` freezes
    on — ptq stores the UN-normalized absmax as its ``w_scale`` (the
    QAT fake-quant convention, divided by qmax at dequant and in
    ``int8_state_dict``), while this helper returns the ready-to-use
    dequant scale. Keep the two in sync through this docstring."""
    w32 = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w32), axis=axis), 1e-8) / qmax
    if scale_dtype is not None:
        scale = scale.astype(scale_dtype)
    wq = jnp.clip(
        jnp.round(w32 / jnp.expand_dims(scale.astype(jnp.float32), axis)),
        -qmax, qmax).astype(jnp.int8)
    return wq, scale
