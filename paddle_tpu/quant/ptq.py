"""Post-training quantization + int8 conversion.

Reference: ``fluid/contrib/slim/quantization/post_training_quantization.py``
(feed calibration batches, collect per-tensor abs-max / histogram scales,
then rewrite to a quantized program) and QuantizationFreezePass (fold
fake-quant into real int8 weights).

TPU-native endpoint: ``Int8Linear`` runs a *real* ``int8 × int8 → int32``
``lax.dot_general`` (the MXU consumes int8 natively at double bf16
throughput) and dequantizes the int32 accumulator with the folded
``act_scale * w_scale / qmax²`` factor — not a simulated float matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.core.module import Module
from paddle_tpu.nn.stateful import map_modules, merge_state, state_tape
from paddle_tpu.quant import functional as QF
from paddle_tpu.quant.qat import (
    QuantConfig, QuantedConv2D, QuantedLinear, quantize_model,
)

__all__ = ["calibrate", "convert_to_int8", "Int8Linear", "int8_state_dict"]


def calibrate(model, batches, config: QuantConfig | None = None, *,
              forward=None):
    """PTQ calibration: wrap quantizable layers, then run calibration
    batches in training-stat mode so every layer's activation EMA scale
    fills in. Returns the calibrated (QAT-structured) model."""
    cfg = config or QuantConfig()
    qmodel = quantize_model(model, cfg)
    forward = forward or (lambda m, b: m(b, training=True))
    for batch in batches:
        with state_tape() as tape:
            forward(qmodel, batch)
        qmodel = merge_state(qmodel, dict(tape))
    return qmodel


class Int8Linear(Module):
    """Frozen int8 linear: weight stored int8, activation quantized on
    entry, int32 accumulation on the MXU, scalar dequant on exit."""

    _nontrainable = ("weight_q", "w_scale", "act_scale")

    def __init__(self, weight_q, w_scale, act_scale, bias, bits: int = 8):
        self.weight_q = weight_q            # int8 [in, out]
        self.w_scale = w_scale              # f32 [out]
        self.act_scale = act_scale          # f32 scalar
        self.bias = bias
        self.qmax = QF.quant_max(bits)

    def __call__(self, x, training: bool = False):
        s_in = jnp.maximum(self.act_scale, 1e-8)
        xq = jnp.clip(jnp.round(x / s_in * self.qmax),
                      -self.qmax, self.qmax).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, self.weight_q,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        deq = s_in / self.qmax * (self.w_scale / self.qmax)
        y = acc.astype(jnp.float32) * deq
        return y + self.bias if self.bias is not None else y


def convert_to_int8(qmodel):
    """QuantizationFreezePass: QAT/calibrated wrappers → real int8 layers
    (Linear only; quanted convs stay fake-quant — int8 convs need a
    layout-specialized kernel, a deliberate keep-simple here)."""

    def fn(m):
        if isinstance(m, QuantedLinear):
            if float(m.act_scale) <= 0:
                raise ValueError(
                    "convert_to_int8: activation scale is uncalibrated "
                    "(act_scale <= 0). Run quant.calibrate() or train "
                    "with QAT before freezing to int8.")
            qmax = QF.quant_max(m.weight_bits)
            # Freeze on the same grid fake-quant trained on: per-channel
            # scales only when the QAT config used them.
            if m.weight_per_channel:
                w_scale = jnp.maximum(
                    jnp.max(jnp.abs(m.weight), axis=(0,)), 1e-8)
            else:
                w_scale = jnp.maximum(jnp.max(jnp.abs(m.weight)), 1e-8)
            wq = jnp.clip(jnp.round(m.weight / w_scale * qmax),
                          -qmax, qmax).astype(jnp.int8)
            return Int8Linear(wq, w_scale, m.act_scale, m.bias,
                              m.weight_bits)
        return m

    return map_modules(fn, qmodel)


def int8_state_dict(model) -> dict[str, np.ndarray]:
    """Export int8 weights + scales (the save_quantized_model artifact)."""
    from paddle_tpu.io.checkpoint import state_dict

    return {k: np.asarray(v) for k, v in state_dict(model).items()}
