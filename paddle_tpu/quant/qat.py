"""Quantization-aware training: swap layers for fake-quanted wrappers.

Reference: ``fluid/contrib/slim/quantization/quantization_pass.py``
(QuantizationTransformPass: rewrites the program, inserting fake_quant on
the inputs/weights of quantizable ops; weight per-channel, activations
moving-average per-tensor). Here the "pass" is a ``map_modules`` sweep
swapping ``nn.Linear``/``nn.Conv2D`` for quantized wrappers — module
surgery instead of graph surgery, same semantics.

Activation scales are running state (like BN statistics): tracked on the
state tape during training-mode forwards and merged back by the trainer,
so QAT composes with the existing ``build_train_step`` unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.core.module import Module
from paddle_tpu.nn.stateful import map_modules, new_uid, record_state
from paddle_tpu.quant import functional as QF

__all__ = ["QuantConfig", "QuantedLinear", "QuantedConv2D",
           "quantize_model"]


@dataclass
class QuantConfig:
    """Mirrors the knobs of the reference's transform pass."""
    weight_bits: int = 8
    activation_bits: int = 8
    weight_per_channel: bool = True
    moving_rate: float = 0.9         # activation scale EMA momentum
    skip_patterns: tuple = ()        # attribute-name substrings to skip


class _QuantedBase(Module):
    _nontrainable = ("act_scale",)

    def _init_quant(self, cfg: QuantConfig):
        self._uid = new_uid()
        self.act_scale = jnp.zeros((), jnp.float32)
        self.weight_bits = cfg.weight_bits
        self.activation_bits = cfg.activation_bits
        self.weight_per_channel = cfg.weight_per_channel
        self.moving_rate = cfg.moving_rate

    def _quant_act(self, x, training: bool):
        if training:
            new_scale = QF.moving_average_abs_max_scale(
                x, jnp.where(self.act_scale > 0, self.act_scale,
                             jnp.max(jnp.abs(jax.lax.stop_gradient(x)))),
                self.moving_rate)
            record_state(self._uid, act_scale=new_scale)
            return QF.fake_quant(x, new_scale, self.activation_bits)
        scale = jnp.where(self.act_scale > 0, self.act_scale,
                          jnp.max(jnp.abs(x)))
        return QF.fake_quant(x, scale, self.activation_bits)

    def _quant_weight(self, w, channel_axis: int):
        if self.weight_per_channel:
            wq, _ = QF.fake_channel_wise_quant_abs_max(
                w, self.weight_bits, axis=channel_axis)
        else:
            wq, _ = QF.fake_quant_abs_max(w, self.weight_bits)
        return wq


class QuantedLinear(_QuantedBase):
    """Linear with fake-quanted input + weight (weight [in, out]:
    per-channel scale along the output axis)."""

    def __init__(self, inner: nn.Linear, cfg: QuantConfig):
        self.weight = inner.weight
        self.bias = inner.bias
        self._init_quant(cfg)

    def __call__(self, x, training: bool = False):
        xq = self._quant_act(x, training)
        wq = self._quant_weight(self.weight, channel_axis=1)
        y = xq @ wq
        return y + self.bias if self.bias is not None else y


class QuantedConv2D(_QuantedBase):
    """Conv2D with fake-quanted input + weight (weight OIHW: per-channel
    scale along O)."""

    def __init__(self, inner: nn.Conv2D, cfg: QuantConfig):
        self.weight = inner.weight
        self.bias = inner.bias
        self.stride = inner.stride
        self.padding = inner.padding
        self.dilation = inner.dilation
        self.groups = inner.groups
        self.data_format = inner.data_format
        self.in_channels = inner.in_channels
        self.out_channels = inner.out_channels
        self._init_quant(cfg)

    def __call__(self, x, training: bool = False):
        from paddle_tpu.nn import functional as F

        xq = self._quant_act(x, training)
        wq = self._quant_weight(self.weight, channel_axis=0)
        return F.conv2d(xq, wq, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


def quantize_model(model, config: QuantConfig | None = None):
    """The QuantizationTransformPass: return a copy of ``model`` with
    quantizable layers wrapped."""
    cfg = config or QuantConfig()

    def fn(m):
        if isinstance(m, nn.Linear):
            return QuantedLinear(m, cfg)
        if isinstance(m, nn.Conv2D):
            return QuantedConv2D(m, cfg)
        return m

    return map_modules(fn, model)
