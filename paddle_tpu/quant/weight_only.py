"""Weight-only int8 quantization for serving/decode.

Reference context: the reference's slim/quantization stack
(``fluid/contrib/slim/quantization/``) is built around fake-quant +
freeze for int8 *compute* (matched here by ``quant/qat.py`` +
``quant/ptq.py``). Weight-only quantization is the serving-era
complement this framework adds for autoregressive decode on TPU:
decode is HBM-bandwidth-bound (every generated token re-reads all
weights), so storing weights int8 halves the dominant traffic while
keeping activations and accumulation in bf16/f32 — no calibration data,
no activation-scale bookkeeping, near-lossless per-channel rounding.

Design notes:
- Per-output-channel symmetric scales. The scale is applied AFTER the
  contraction — ``x @ (q·s) == (x @ q)·s`` for a per-out-channel ``s``
  — so the matmul's rhs is a bare ``convert(int8)`` that XLA fuses into
  the dot's operand stream (no dequantized [in, out] copy in HBM).
- ``quantize_weights_int8`` is a model transform (``map_modules``): any
  ``nn.Linear`` becomes a ``WeightOnlyInt8Linear`` with the same call
  contract and the same partition specs (weight spec carries over;
  the scale inherits the output-dim axis), so TP-sharded decode works
  unchanged. Embeddings are left alone (a gather reads one row per
  token — not the bandwidth problem).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.module import Module
from paddle_tpu.nn.common import Linear
from paddle_tpu.nn.stateful import map_modules

__all__ = ["WeightOnlyInt8Linear", "quantize_weights_int8"]


class WeightOnlyInt8Linear(Module):
    """Drop-in Linear with int8-stored weights and bf16/f32 compute."""

    _nontrainable = ("weight_q", "w_scale")

    def __init__(self, weight_q, w_scale, bias, compute_dtype,
                 pspecs=None):
        self.weight_q = weight_q          # int8 [in, out]
        self.w_scale = w_scale            # stored in the compute dtype [out]
        self.bias = bias
        self.compute_dtype = jnp.dtype(compute_dtype).name
        if pspecs is not None:
            self._pspecs = pspecs

    @property
    def weight(self):
        """Dequantized weight — keeps consumers that read
        ``linear.weight`` working (tied-embedding losses, FLOPs
        counters); prefer ``__call__`` on hot paths (this materializes
        the full matrix)."""
        dt = jnp.dtype(self.compute_dtype)
        return (self.weight_q.astype(dt)
                * self.w_scale.astype(dt)[..., None, :])

    def __call__(self, x):
        from paddle_tpu import amp as amp_mod

        # honor an active autocast scope the way F.linear's allow-list
        # cast does; otherwise compute in the quantized model's dtype
        dt = amp_mod.active_dtype("linear") or jnp.dtype(self.compute_dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt:
            x = x.astype(dt)
        y = jnp.dot(x, self.weight_q.astype(dt)) * self.w_scale.astype(dt)
        if self.bias is not None:
            y = y + self.bias.astype(dt)
        return y


def quantize_weights_int8(model):
    """Quantize every ``nn.Linear`` in ``model`` to weight-only int8
    (per-output-channel symmetric). Returns a new model; the original
    is untouched. Typically applied to a trained/loaded model right
    before ``models.generation.generate`` or a Predictor export."""

    from paddle_tpu.nn.moe import MoEMLP
    from paddle_tpu.quant.functional import channelwise_int8_freeze

    def fn(m):
        if isinstance(m, MoEMLP):
            # expert tensors [E, in, out]: per-(expert, out-channel)
            # scales over the contraction dim (axis -2), applied after
            # the expert einsums (nn/moe.py _experts). Expert weights
            # dominate an MoE decode step's HBM reads — every expert is
            # resident even though only top-k route per token — so this
            # is the family where halving the bytes pays most.
            wg, sg = channelwise_int8_freeze(m.w_gate, axis=-2,
                                             scale_dtype=m.w_gate.dtype)
            wu, su = channelwise_int8_freeze(m.w_up, axis=-2,
                                             scale_dtype=m.w_up.dtype)
            wd, sd = channelwise_int8_freeze(m.w_down, axis=-2,
                                             scale_dtype=m.w_down.dtype)
            pspecs = dict(m._pspecs)
            pspecs.update({
                "w_gate_scale": P("ep", "tp"),
                "w_up_scale": P("ep", "tp"),
                "w_down_scale": P("ep", "fsdp"),
            })
            return m.replace(
                w_gate=wg, w_up=wu, w_down=wd, w_gate_scale=sg,
                w_up_scale=su, w_down_scale=sd,
                _pspecs=tuple(pspecs.items()),
                _nontrainable=("w_gate", "w_up", "w_down", "w_gate_scale",
                               "w_up_scale", "w_down_scale"))
        if not isinstance(m, Linear):
            return m
        w = m.weight
        # reduce over the input dim (axis -2): per-output-channel scales,
        # and scan-stacked Linears ([L, in, out] weights inside
        # ScannedBlocks) keep their leading layer axis on every leaf.
        # scale_dtype=w.dtype quantizes against the dtype-rounded scale,
        # so dequant with the stored (bf16) scale stays on the freeze
        # grid and the scale/2 error bound holds for bf16 models too
        wq, scale = channelwise_int8_freeze(w, axis=-2,
                                            scale_dtype=w.dtype)
        pspecs = None
        if hasattr(m, "_pspecs"):
            by_name = dict(m._pspecs)
            wspec = by_name.get("weight")
            out_axis = (wspec[-1] if wspec is not None and len(wspec) >= 2
                        else None)
            pspecs = (("weight_q", wspec) if wspec is not None
                      else ("weight_q", P(None, None)),
                      ("w_scale", P(out_axis)),
                      ("bias", by_name.get("bias", P(out_axis))))
        return WeightOnlyInt8Linear(wq, scale, m.bias, w.dtype, pspecs)

    return map_modules(fn, model)
