"""paddle_tpu.native — C++ host runtime (sparse tables, packed data feed).

The TPU compute path is JAX/XLA/Pallas; the *host* runtime around it is
native C++, like the reference's: sparse parameter tables
(reference ``operators/distributed/large_scale_kv.h:1``,
``paddle/fluid/distributed/table/common_sparse_table.cc``) and the packed
data feed (``framework/data_feed.h:678`` MultiSlotInMemoryDataFeed).
Compiled on first use (see ``build.py``) and bound via ctypes.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from paddle_tpu.native.build import build_library

__all__ = ["NativeSparseTable", "lib", "OPTIMIZERS"]

OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2}

_lib = None


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(build_library())
        _declare(_lib)
    return _lib


def _declare(L: ctypes.CDLL) -> None:
    i64, f32, vp, cp = (ctypes.c_int64, ctypes.c_float, ctypes.c_void_p,
                        ctypes.c_char_p)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    L.pt_sparse_table_create.restype = vp
    L.pt_sparse_table_create.argtypes = [i64, ctypes.c_int, f32, f32,
                                         ctypes.c_uint64, ctypes.c_int]
    L.pt_sparse_table_free.argtypes = [vp]
    L.pt_sparse_table_size.restype = i64
    L.pt_sparse_table_size.argtypes = [vp]
    L.pt_sparse_table_pull.argtypes = [vp, i64p, i64, f32p]
    L.pt_sparse_table_push_grad.argtypes = [vp, i64p, i64, f32p]
    L.pt_sparse_table_push_delta.argtypes = [vp, i64p, i64, f32p]
    L.pt_sparse_table_assign.argtypes = [vp, i64p, i64, f32p]
    L.pt_sparse_table_keys.restype = i64
    L.pt_sparse_table_keys.argtypes = [vp, i64p, i64]
    L.pt_sparse_table_save.restype = ctypes.c_int
    L.pt_sparse_table_save.argtypes = [vp, cp]
    L.pt_sparse_table_load.restype = ctypes.c_int
    L.pt_sparse_table_load.argtypes = [vp, cp]
    L.pt_sparse_table_set_lr.argtypes = [vp, f32]


def _ids_ptr(ids: np.ndarray):
    return ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeSparseTable:
    """ctypes handle over the C++ sharded sparse table."""

    def __init__(self, dim: int, *, optimizer: str = "sgd", lr: float = 0.01,
                 init_scale: float = 0.01, seed: int = 0, shards: int = 16):
        if optimizer not in OPTIMIZERS:
            raise ValueError(f"optimizer {optimizer!r}: "
                             f"choose from {sorted(OPTIMIZERS)}")
        self.dim = int(dim)
        self.optimizer = optimizer
        self._h = lib().pt_sparse_table_create(
            self.dim, OPTIMIZERS[optimizer], float(lr), float(init_scale),
            int(seed), int(shards))
        if not self._h:
            raise RuntimeError("sparse table creation failed")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and _lib is not None:
            _lib.pt_sparse_table_free(h)

    def __len__(self) -> int:
        return int(lib().pt_sparse_table_size(self._h))

    def _check_ids(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.int64).reshape(-1)
        return ids

    def pull(self, ids) -> np.ndarray:
        """Rows for ``ids`` (missing rows materialize deterministically)."""
        ids = self._check_ids(ids)
        out = np.empty((ids.shape[0], self.dim), np.float32)
        lib().pt_sparse_table_pull(self._h, _ids_ptr(ids), ids.shape[0],
                                   _f32_ptr(out))
        return out

    def push_grad(self, ids, grads) -> None:
        """Apply one server-side optimizer step from (possibly duplicate-
        id) row gradients."""
        ids = self._check_ids(ids)
        grads = np.ascontiguousarray(grads, dtype=np.float32).reshape(
            ids.shape[0], self.dim)
        lib().pt_sparse_table_push_grad(self._h, _ids_ptr(ids),
                                        ids.shape[0], _f32_ptr(grads))

    def push_delta(self, ids, deltas) -> None:
        """geo-SGD: add raw parameter deltas (no optimizer slots)."""
        ids = self._check_ids(ids)
        deltas = np.ascontiguousarray(deltas, dtype=np.float32).reshape(
            ids.shape[0], self.dim)
        lib().pt_sparse_table_push_delta(self._h, _ids_ptr(ids),
                                         ids.shape[0], _f32_ptr(deltas))

    def assign(self, ids, values) -> None:
        ids = self._check_ids(ids)
        values = np.ascontiguousarray(values, dtype=np.float32).reshape(
            ids.shape[0], self.dim)
        lib().pt_sparse_table_assign(self._h, _ids_ptr(ids), ids.shape[0],
                                     _f32_ptr(values))

    def keys(self) -> np.ndarray:
        cap = len(self) + 64
        out = np.empty(cap, np.int64)
        n = lib().pt_sparse_table_keys(self._h, _ids_ptr(out), cap)
        return np.sort(out[:n])

    def set_lr(self, lr: float) -> None:
        lib().pt_sparse_table_set_lr(self._h, float(lr))

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if lib().pt_sparse_table_save(self._h, path.encode()) != 0:
            raise IOError(f"sparse table save failed: {path}")

    def load(self, path: str) -> None:
        if lib().pt_sparse_table_load(self._h, path.encode()) != 0:
            raise IOError(f"sparse table load failed: {path}")
