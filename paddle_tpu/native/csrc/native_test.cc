// C++-level unit tests for the native host runtime (sparse table + data
// feed), mirroring the reference's colocated *_test.cc files (e.g.
// async_sparse_param_update_recorder_test.cc). Plain assert-based — no
// gtest dependency in this image; built and executed by
// tests/test_ps.py::test_native_cc_unit_tests.

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* pt_sparse_table_create(long long dim, int optimizer, float lr,
                             float init_scale, unsigned long long seed,
                             int shards);
void pt_sparse_table_free(void* t);
long long pt_sparse_table_size(void* t);
void pt_sparse_table_pull(void* t, const long long* ids, long long n,
                          float* out);
void pt_sparse_table_push_grad(void* t, const long long* ids, long long n,
                               const float* grads);
void pt_sparse_table_push_delta(void* t, const long long* ids, long long n,
                                const float* deltas);
void* pt_feed_create(const int* slot_types, int n_slots);
void pt_feed_free(void* h);
long long pt_feed_load_file(void* h, const char* path);
long long pt_feed_num_records(void* h);
long long pt_feed_batch_count(void* h, int slot, long long start,
                              long long bs);
long long pt_feed_fill_batch(void* h, int slot, long long start,
                             long long bs, void* values, long long* offsets);
}

static void test_table_basic() {
  void* t = pt_sparse_table_create(4, /*sgd*/ 0, 0.5f, 0.1f, 7, 8);
  assert(t);
  long long ids[2] = {3, 9};
  float rows[8];
  pt_sparse_table_pull(t, ids, 2, rows);
  for (int i = 0; i < 8; ++i) assert(std::fabs(rows[i]) <= 0.1f + 1e-6f);
  assert(pt_sparse_table_size(t) == 2);

  float g[8];
  for (int i = 0; i < 8; ++i) g[i] = 1.0f;
  pt_sparse_table_push_grad(t, ids, 2, g);
  float after[8];
  pt_sparse_table_pull(t, ids, 2, after);
  for (int i = 0; i < 8; ++i)
    assert(std::fabs(after[i] - (rows[i] - 0.5f)) < 1e-6f);
  pt_sparse_table_free(t);
  std::puts("table_basic ok");
}

static void test_table_concurrent_pushes() {
  // shard locks: concurrent disjoint-id pushes must all land
  void* t = pt_sparse_table_create(2, 0, 1.0f, 0.0f, 1, 4);
  const int kThreads = 8, kIters = 100;
  std::vector<std::thread> ts;
  for (int w = 0; w < kThreads; ++w) {
    ts.emplace_back([&, w] {
      long long id = w;
      float g[2] = {1.0f, -1.0f};
      for (int i = 0; i < kIters; ++i)
        pt_sparse_table_push_grad(t, &id, 1, g);
    });
  }
  for (auto& th : ts) th.join();
  for (long long w = 0; w < kThreads; ++w) {
    float row[2];
    pt_sparse_table_pull(t, &w, 1, row);
    assert(std::fabs(row[0] + (float)kIters) < 1e-3f);  // 0 - lr*sum(g)
    assert(std::fabs(row[1] - (float)kIters) < 1e-3f);
  }
  pt_sparse_table_free(t);
  std::puts("table_concurrent ok");
}

static void test_feed_roundtrip(const char* tmpdir) {
  char path[512];
  std::snprintf(path, sizeof(path), "%s/feed.txt", tmpdir);
  FILE* f = std::fopen(path, "w");
  std::fputs("2 10 20 1 0.5\n1 30 1 1.5\n", f);  // ids slot + float slot
  std::fclose(f);

  int types[2] = {0, 1};
  void* h = pt_feed_create(types, 2);
  assert(pt_feed_load_file(h, path) == 2);
  assert(pt_feed_num_records(h) == 2);
  assert(pt_feed_batch_count(h, 0, 0, 2) == 3);

  long long vals[3];
  long long offsets[3];
  long long n = pt_feed_fill_batch(h, 0, 0, 2, vals, offsets);
  assert(n == 2);
  assert(offsets[0] == 0 && offsets[1] == 2 && offsets[2] == 3);
  assert(vals[0] == 10 && vals[1] == 20 && vals[2] == 30);

  float fvals[2];
  long long foff[3];
  pt_feed_fill_batch(h, 1, 0, 2, fvals, foff);
  assert(std::fabs(fvals[0] - 0.5f) < 1e-6f);
  assert(std::fabs(fvals[1] - 1.5f) < 1e-6f);
  pt_feed_free(h);
  std::puts("feed_roundtrip ok");
}

int main(int argc, char** argv) {
  test_table_basic();
  test_table_concurrent_pushes();
  test_feed_roundtrip(argc > 1 ? argv[1] : "/tmp");
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
