// Host-side sparse parameter table for the parameter-server stack.
//
// TPU-native counterpart of the reference's large-scale KV
// (reference /root/reference/paddle/fluid/operators/distributed/large_scale_kv.h:1
// SparseVariable: sharded unordered_map of id -> {param + optimizer slots},
// and paddle/fluid/distributed/table/common_sparse_table.cc): embeddings too
// large for HBM live in host RAM; workers pull rows for the ids in a batch,
// run the dense math on the TPU, and push gradients back; the optimizer
// update happens server-side (per-row SGD/AdaGrad/Adam), which is what
// makes async/geo modes possible.
//
// Design deltas from the reference, on purpose:
//  - init-on-first-touch is a *deterministic* per-id hash RNG (splitmix64
//    of table seed + id), so any worker/any host materializes identical
//    rows without coordination — the reference re-seeds a global generator
//    and must broadcast initialized rows instead.
//  - the value layout is [param(dim) | slot0(dim) | slot1(dim) | t] in one
//    contiguous allocation per row (cache-friendly pull).
//  - C ABI + ctypes instead of pybind (not available in this image).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

enum Optimizer : int { kSGD = 0, kAdaGrad = 1, kAdam = 2 };

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// uniform in [-scale, scale), deterministic in (seed, id, j)
inline float init_value(uint64_t seed, int64_t id, int64_t j, float scale) {
  uint64_t h = splitmix64(seed ^ splitmix64(static_cast<uint64_t>(id) +
                                            0x51ed270b * (uint64_t)(j + 1)));
  double u = (h >> 11) * (1.0 / 9007199254740992.0);  // [0, 1)
  return static_cast<float>((2.0 * u - 1.0) * scale);
}

struct Shard {
  std::mutex mu;
  std::unordered_map<int64_t, std::vector<float>> rows;
};

struct SparseTable {
  int64_t dim;
  int optimizer;
  float lr;
  float init_scale;
  uint64_t seed;
  int n_shards;
  // adam hyperparams (fixed defaults; row-local step t lives in the row)
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
  std::vector<Shard> shards;

  SparseTable(int64_t d, int opt, float lr_, float scale, uint64_t seed_,
              int ns)
      : dim(d), optimizer(opt), lr(lr_), init_scale(scale), seed(seed_),
        n_shards(ns), shards(ns) {}

  size_t value_size() const {
    switch (optimizer) {
      case kSGD: return dim;
      case kAdaGrad: return 2 * dim;
      case kAdam: return 3 * dim + 1;  // param, m, v, t
    }
    return dim;
  }

  Shard& shard_of(int64_t id) {
    return shards[splitmix64(static_cast<uint64_t>(id)) % n_shards];
  }

  std::vector<float>& row(int64_t id, bool* created = nullptr) {
    // caller must hold the shard lock
    Shard& s = shard_of(id);
    auto it = s.rows.find(id);
    if (it == s.rows.end()) {
      std::vector<float> v(value_size(), 0.0f);
      for (int64_t j = 0; j < dim; ++j)
        v[j] = init_value(seed, id, j, init_scale);
      it = s.rows.emplace(id, std::move(v)).first;
      if (created) *created = true;
    }
    return it->second;
  }

  void pull(const int64_t* ids, int64_t n, float* out) {
    for (int64_t i = 0; i < n; ++i) {
      Shard& s = shard_of(ids[i]);
      std::lock_guard<std::mutex> g(s.mu);
      const std::vector<float>& v = row(ids[i]);
      std::memcpy(out + i * dim, v.data(), dim * sizeof(float));
    }
  }

  void apply_update(std::vector<float>& v, const float* g) {
    float* p = v.data();
    switch (optimizer) {
      case kSGD:
        for (int64_t j = 0; j < dim; ++j) p[j] -= lr * g[j];
        break;
      case kAdaGrad: {
        float* G = p + dim;
        for (int64_t j = 0; j < dim; ++j) {
          G[j] += g[j] * g[j];
          p[j] -= lr * g[j] / (std::sqrt(G[j]) + 1e-6f);
        }
        break;
      }
      case kAdam: {
        float* m = p + dim;
        float* vv = p + 2 * dim;
        float& t = p[3 * dim];
        t += 1.0f;
        float bc1 = 1.0f - std::pow(beta1, t);
        float bc2 = 1.0f - std::pow(beta2, t);
        for (int64_t j = 0; j < dim; ++j) {
          m[j] = beta1 * m[j] + (1 - beta1) * g[j];
          vv[j] = beta2 * vv[j] + (1 - beta2) * g[j] * g[j];
          p[j] -= lr * (m[j] / bc1) / (std::sqrt(vv[j] / bc2) + eps);
        }
        break;
      }
    }
  }

  void push_grad(const int64_t* ids, int64_t n, const float* grads) {
    // merge duplicate ids first (the reference merges SelectedRows grads
    // before the update) so each row takes one optimizer step per push
    std::unordered_map<int64_t, std::vector<float>> merged;
    merged.reserve(n);
    for (int64_t i = 0; i < n; ++i) {
      auto& acc = merged[ids[i]];
      if (acc.empty()) acc.assign(dim, 0.0f);
      const float* g = grads + i * dim;
      for (int64_t j = 0; j < dim; ++j) acc[j] += g[j];
    }
    for (auto& kv : merged) {
      Shard& s = shard_of(kv.first);
      std::lock_guard<std::mutex> g(s.mu);
      apply_update(row(kv.first), kv.second.data());
    }
  }

  void push_delta(const int64_t* ids, int64_t n, const float* deltas) {
    // geo-SGD: add raw parameter deltas (no optimizer state touched)
    for (int64_t i = 0; i < n; ++i) {
      Shard& s = shard_of(ids[i]);
      std::lock_guard<std::mutex> g(s.mu);
      std::vector<float>& v = row(ids[i]);
      const float* d = deltas + i * dim;
      for (int64_t j = 0; j < dim; ++j) v[j] += d[j];
    }
  }

  void assign(const int64_t* ids, int64_t n, const float* vals) {
    for (int64_t i = 0; i < n; ++i) {
      Shard& s = shard_of(ids[i]);
      std::lock_guard<std::mutex> g(s.mu);
      std::vector<float>& v = row(ids[i]);
      std::memcpy(v.data(), vals + i * dim, dim * sizeof(float));
    }
  }

  int64_t size() {
    int64_t total = 0;
    for (auto& s : shards) {
      std::lock_guard<std::mutex> g(s.mu);
      total += static_cast<int64_t>(s.rows.size());
    }
    return total;
  }

  int64_t keys(int64_t* out, int64_t cap) {
    int64_t k = 0;
    for (auto& s : shards) {
      std::lock_guard<std::mutex> g(s.mu);
      for (auto& kv : s.rows) {
        if (k >= cap) return k;
        out[k++] = kv.first;
      }
    }
    return k;
  }

  bool save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    const uint64_t magic = 0x50545350u;  // "PTSP"
    int64_t count = size();
    size_t vs = value_size();
    std::fwrite(&magic, sizeof(magic), 1, f);
    std::fwrite(&dim, sizeof(dim), 1, f);
    std::fwrite(&optimizer, sizeof(optimizer), 1, f);
    std::fwrite(&count, sizeof(count), 1, f);
    for (auto& s : shards) {
      std::lock_guard<std::mutex> g(s.mu);
      for (auto& kv : s.rows) {
        std::fwrite(&kv.first, sizeof(int64_t), 1, f);
        std::fwrite(kv.second.data(), sizeof(float), vs, f);
      }
    }
    std::fclose(f);
    return true;
  }

  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    uint64_t magic = 0;
    int64_t d = 0, count = 0;
    int opt = 0;
    bool ok = std::fread(&magic, sizeof(magic), 1, f) == 1 &&
              std::fread(&d, sizeof(d), 1, f) == 1 &&
              std::fread(&opt, sizeof(opt), 1, f) == 1 &&
              std::fread(&count, sizeof(count), 1, f) == 1;
    if (!ok || magic != 0x50545350u || d != dim || opt != optimizer) {
      std::fclose(f);
      return false;
    }
    size_t vs = value_size();
    std::vector<float> buf(vs);
    for (int64_t i = 0; i < count; ++i) {
      int64_t id;
      if (std::fread(&id, sizeof(id), 1, f) != 1 ||
          std::fread(buf.data(), sizeof(float), vs, f) != vs) {
        std::fclose(f);
        return false;
      }
      Shard& s = shard_of(id);
      std::lock_guard<std::mutex> g(s.mu);
      s.rows[id] = buf;
    }
    std::fclose(f);
    return true;
  }
};

}  // namespace

extern "C" {

void* pt_sparse_table_create(int64_t dim, int optimizer, float lr,
                             float init_scale, uint64_t seed, int shards) {
  if (dim <= 0 || shards <= 0) return nullptr;
  return new SparseTable(dim, optimizer, lr, init_scale, seed, shards);
}

void pt_sparse_table_free(void* t) { delete static_cast<SparseTable*>(t); }

int64_t pt_sparse_table_size(void* t) {
  return static_cast<SparseTable*>(t)->size();
}

void pt_sparse_table_pull(void* t, const int64_t* ids, int64_t n,
                          float* out) {
  static_cast<SparseTable*>(t)->pull(ids, n, out);
}

void pt_sparse_table_push_grad(void* t, const int64_t* ids, int64_t n,
                               const float* grads) {
  static_cast<SparseTable*>(t)->push_grad(ids, n, grads);
}

void pt_sparse_table_push_delta(void* t, const int64_t* ids, int64_t n,
                                const float* deltas) {
  static_cast<SparseTable*>(t)->push_delta(ids, n, deltas);
}

void pt_sparse_table_assign(void* t, const int64_t* ids, int64_t n,
                            const float* vals) {
  static_cast<SparseTable*>(t)->assign(ids, n, vals);
}

int64_t pt_sparse_table_keys(void* t, int64_t* out, int64_t cap) {
  return static_cast<SparseTable*>(t)->keys(out, cap);
}

int pt_sparse_table_save(void* t, const char* path) {
  return static_cast<SparseTable*>(t)->save(path) ? 0 : -1;
}

int pt_sparse_table_load(void* t, const char* path) {
  return static_cast<SparseTable*>(t)->load(path) ? 0 : -1;
}

void pt_sparse_table_set_lr(void* t, float lr) {
  static_cast<SparseTable*>(t)->lr = lr;
}

}  // extern "C"
