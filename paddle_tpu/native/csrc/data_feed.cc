// Packed multi-slot data feed: native parse + batch for recommender IO.
//
// TPU-native counterpart of the reference's MultiSlot feeds
// (reference /root/reference/paddle/fluid/framework/data_feed.h:660,678
// MultiSlotDataFeed / MultiSlotInMemoryDataFeed; line format parsed in
// data_feed.cc ParseOneInstance: per slot "<num> <v>*num", values uint64
// ids or floats). Same wire format; different architecture:
//
//  - records land in per-slot packed arenas (one contiguous int64/float
//    buffer per slot + per-record (offset,count)) instead of
//    per-instance MultiSlotType vectors — batch assembly is then pure
//    memcpy into caller-provided buffers, and those buffers go straight
//    into jax.device_put (the zero-copy host→device handoff; no
//    LoDTensor intermediary).
//  - sparse slots batch as CSR (values + row offsets), which is exactly
//    the (ids, segment) layout jax segment ops and the PS pull path want.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

namespace {

enum SlotType : int { kInt64 = 0, kFloat = 1 };

struct SlotArena {
  int type;
  std::vector<int64_t> ints;
  std::vector<float> floats;
  // per record: start offset + count in the arena
  std::vector<int64_t> starts;
  std::vector<int64_t> counts;

  size_t size_at(int64_t rec) const { return counts[rec]; }
};

struct DataFeed {
  std::vector<SlotArena> slots;
  int64_t n_records = 0;
  std::vector<int64_t> order;  // shuffle indirection

  explicit DataFeed(const int* types, int n) {
    slots.resize(n);
    for (int i = 0; i < n; ++i) slots[i].type = types[i];
  }
};

// parse one line: for each slot "<num> <v>*num"; returns false on error
bool parse_line(DataFeed* f, const char* str) {
  char* end = const_cast<char*>(str);
  for (auto& slot : f->slots) {
    long num = std::strtol(end, &end, 10);
    if (num <= 0) return false;  // reference enforces num != 0 too
    slot.starts.push_back(slot.type == kInt64
                              ? (int64_t)slot.ints.size()
                              : (int64_t)slot.floats.size());
    slot.counts.push_back(num);
    if (slot.type == kInt64) {
      for (long j = 0; j < num; ++j)
        slot.ints.push_back((int64_t)std::strtoll(end, &end, 10));
    } else {
      for (long j = 0; j < num; ++j)
        slot.floats.push_back(std::strtof(end, &end));
    }
  }
  return true;
}

}  // namespace

extern "C" {

void* pt_feed_create(const int* slot_types, int n_slots) {
  if (n_slots <= 0) return nullptr;
  return new DataFeed(slot_types, n_slots);
}

void pt_feed_free(void* h) { delete static_cast<DataFeed*>(h); }

// returns records added, or -(line_number) of the first bad line
int64_t pt_feed_load_file(void* h, const char* path) {
  DataFeed* f = static_cast<DataFeed*>(h);
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  int64_t added = 0, lineno = 0;
  std::string line;
  std::vector<char> buf(1 << 16);
  while (std::fgets(buf.data(), (int)buf.size(), fp)) {
    ++lineno;
    line.assign(buf.data());
    // reassemble lines longer than the buffer
    while (!line.empty() && line.back() != '\n' &&
           std::fgets(buf.data(), (int)buf.size(), fp))
      line += buf.data();
    if (line.find_first_not_of(" \t\r\n") == std::string::npos) continue;
    if (!parse_line(f, line.c_str())) {
      std::fclose(fp);
      return -lineno;
    }
    ++added;
  }
  std::fclose(fp);
  f->n_records += added;
  f->order.resize(f->n_records);
  for (int64_t i = 0; i < f->n_records; ++i) f->order[i] = i;
  return added;
}

int64_t pt_feed_num_records(void* h) {
  return static_cast<DataFeed*>(h)->n_records;
}

void pt_feed_shuffle(void* h, uint64_t seed) {
  DataFeed* f = static_cast<DataFeed*>(h);
  std::mt19937_64 rng(seed);
  std::shuffle(f->order.begin(), f->order.end(), rng);
}

// total value count for [start, start+bs) in one slot (buffer sizing)
int64_t pt_feed_batch_count(void* h, int slot, int64_t start, int64_t bs) {
  DataFeed* f = static_cast<DataFeed*>(h);
  const SlotArena& s = f->slots[slot];
  int64_t total = 0;
  for (int64_t i = start; i < start + bs && i < f->n_records; ++i)
    total += s.counts[f->order[i]];
  return total;
}

// fill CSR batch: values (int64 or float buffer) + offsets[bs+1]
int64_t pt_feed_fill_batch(void* h, int slot, int64_t start, int64_t bs,
                           void* values, int64_t* offsets) {
  DataFeed* f = static_cast<DataFeed*>(h);
  const SlotArena& s = f->slots[slot];
  int64_t pos = 0, row = 0;
  for (int64_t i = start; i < start + bs && i < f->n_records; ++i, ++row) {
    int64_t rec = f->order[i];
    offsets[row] = pos;
    int64_t n = s.counts[rec], st = s.starts[rec];
    if (s.type == kInt64)
      std::memcpy(static_cast<int64_t*>(values) + pos, s.ints.data() + st,
                  n * sizeof(int64_t));
    else
      std::memcpy(static_cast<float*>(values) + pos, s.floats.data() + st,
                  n * sizeof(float));
    pos += n;
  }
  offsets[row] = pos;
  return row;  // records actually filled (may be < bs at the tail)
}

}  // extern "C"
