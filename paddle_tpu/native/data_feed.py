"""Python surface of the native multi-slot data feed.

See ``csrc/data_feed.cc`` for the format/architecture notes (reference
``framework/data_feed.h:678`` MultiSlotInMemoryDataFeed). Batches come
out as numpy views ready for ``jax.device_put``: sparse slots as
``(values[int64], offsets[int64, bs+1])`` CSR pairs (the lod of the
reference's LoDTensor), dense float slots as ``[bs, dim]`` when every
record agrees on ``dim``.
"""

from __future__ import annotations

import ctypes

import numpy as np

from paddle_tpu.native import lib

__all__ = ["NativeDataFeed"]

_TYPES = {"int64": 0, "float": 1}


def _declare(L):
    if getattr(L, "_feed_declared", False):
        return L
    i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int)
    vp = ctypes.c_void_p
    i64p = ctypes.POINTER(ctypes.c_int64)
    L.pt_feed_create.restype = vp
    L.pt_feed_create.argtypes = [i32p, ctypes.c_int]
    L.pt_feed_free.argtypes = [vp]
    L.pt_feed_load_file.restype = i64
    L.pt_feed_load_file.argtypes = [vp, ctypes.c_char_p]
    L.pt_feed_num_records.restype = i64
    L.pt_feed_num_records.argtypes = [vp]
    L.pt_feed_shuffle.argtypes = [vp, ctypes.c_uint64]
    L.pt_feed_batch_count.restype = i64
    L.pt_feed_batch_count.argtypes = [vp, ctypes.c_int, i64, i64]
    L.pt_feed_fill_batch.restype = i64
    L.pt_feed_fill_batch.argtypes = [vp, ctypes.c_int, i64, i64, vp, i64p]
    L._feed_declared = True
    return L


class NativeDataFeed:
    """In-memory multi-slot feed: load text files, global shuffle, iterate
    packed batches.

    ``slots`` is an ordered ``{name: "int64" | "float"}`` mapping matching
    the file's slot order.
    """

    def __init__(self, slots: dict[str, str]):
        self.slot_names = list(slots)
        self.slot_types = [slots[n] for n in self.slot_names]
        for t in self.slot_types:
            if t not in _TYPES:
                raise ValueError(f"slot type {t!r}")
        self._L = _declare(lib())
        arr = (ctypes.c_int * len(self.slot_types))(
            *[_TYPES[t] for t in self.slot_types])
        self._h = self._L.pt_feed_create(arr, len(self.slot_types))
        if not self._h:
            raise RuntimeError("feed creation failed")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._L.pt_feed_free(h)

    def load_file(self, path: str) -> int:
        n = self._L.pt_feed_load_file(self._h, str(path).encode())
        if n < 0:
            raise ValueError(f"parse error in {path} at line {-n}")
        return int(n)

    def __len__(self) -> int:
        return int(self._L.pt_feed_num_records(self._h))

    def global_shuffle(self, seed: int = 0) -> None:
        """Shuffle record order (Dataset::GlobalShuffle analogue — one
        host's share; cross-host the sampler shards by rank first)."""
        self._L.pt_feed_shuffle(self._h, int(seed))

    def _slot_batch(self, si: int, start: int, bs: int):
        total = self._L.pt_feed_batch_count(self._h, si, start, bs)
        is_int = self.slot_types[si] == "int64"
        values = np.empty(total, np.int64 if is_int else np.float32)
        offsets = np.empty(bs + 1, np.int64)
        n = self._L.pt_feed_fill_batch(
            self._h, si, start, bs, values.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return values, offsets[:n + 1], int(n)

    def batches(self, batch_size: int, *, drop_last: bool = False,
                dense: bool = True):
        """Yield ``{slot: (values, offsets)}`` CSR batches; fixed-width
        float slots become ``[bs, dim]`` arrays when ``dense``."""
        n = len(self)
        start = 0
        while start < n:
            bs = min(batch_size, n - start)
            if bs < batch_size and drop_last:
                return
            out = {}
            for si, name in enumerate(self.slot_names):
                values, offsets, filled = self._slot_batch(si, start, bs)
                widths = np.diff(offsets)
                if (dense and self.slot_types[si] == "float"
                        and widths.size and (widths == widths[0]).all()):
                    out[name] = values.reshape(filled, widths[0])
                else:
                    out[name] = (values, offsets)
            yield out
            start += bs
