"""Build the native host-runtime library (g++ → .so, loaded via ctypes).

The reference's native layer is CMake-built C++ linked into the pybind
module (``paddle/fluid/pybind/pybind.cc:353``); here the host runtime is a
small self-contained C++17 library compiled on first import and cached by
source hash. ctypes replaces pybind (not available in this image); the
arrays crossing the boundary are plain contiguous buffers so there is no
marshalling cost either way.
"""

from __future__ import annotations

import hashlib
import os
import platform
import subprocess
import threading

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")
_SOURCES = ["sparse_table.cc", "data_feed.cc"]
_lock = threading.Lock()


def _source_hash() -> str:
    h = hashlib.sha256()
    for name in _SOURCES:
        path = os.path.join(_SRC_DIR, name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:16]


def _platform_tag() -> str:
    # Key the cache on arch + libc so a binary built elsewhere (or on a
    # different libc) is never dlopen'd — it triggers a rebuild instead.
    libc, ver = platform.libc_ver()
    return f"{platform.machine()}-{libc or 'unknown'}{ver}"


def build_library() -> str:
    """Compile (if stale) and return the path to the shared library."""
    with _lock:
        tag = f"{_platform_tag()}-{_source_hash()}"
        so_path = os.path.join(_BUILD_DIR, f"libptnative-{tag}.so")
        if os.path.exists(so_path):
            return so_path
        os.makedirs(_BUILD_DIR, exist_ok=True)
        srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES
                if os.path.exists(os.path.join(_SRC_DIR, s))]
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
               "-o", so_path + ".tmp", *srcs]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed:\n{e.stderr}") from None
        os.replace(so_path + ".tmp", so_path)
        return so_path
