"""Quantization-aware training then int8 freeze on a toy classifier.

    python examples/qat_mnist_style.py [--steps 60]
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import nn, quant
from paddle_tpu import optimizer as optim
from paddle_tpu.parallel import mesh as M
from paddle_tpu.vision.datasets import RandomImageDataset


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    args = ap.parse_args()

    paddle_tpu.seed(0)
    train = RandomImageDataset(256, (784,), num_classes=4, seed=0)
    x = jnp.asarray(np.stack([train[i][0] for i in range(256)]))
    y = jnp.asarray(np.asarray([train[i][1] for i in range(256)]))

    model = quant.quantize_model(
        nn.Sequential(nn.Linear(784, 64), nn.ReLU(), nn.Linear(64, 4)))
    mesh = M.create_mesh({"dp": 1}, jax.devices()[:1])

    def loss_fn(m, batch, training=True):
        from paddle_tpu.nn import functional as F
        logits = m(batch["x"], training=training)
        return F.cross_entropy(logits.astype(jnp.float32), batch["y"])

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})
        for i in range(args.steps):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            if i % 20 == 0 or i == args.steps - 1:
                print(f"step {i}: loss={float(metrics['loss']):.4f}")

    int8_model = quant.convert_to_int8(state.model)
    logits = jax.jit(lambda m, v: m(v))(int8_model, x)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == y))
    print(f"int8 accuracy: {acc:.3f} "
          f"(weights {int8_model.layers[0].weight_q.dtype})")


if __name__ == "__main__":
    main()
