"""Deep gradient compression over the data-parallel axis.

Trains a tiny Llama under DGC (reference: DGCMomentumOptimizer,
``fluid/optimizer.py:1183``): dense warmup steps, a sparsity ramp, then
99%-sparse top-k gradient exchange — the configuration aimed at
multi-host data parallelism over DCN, where cutting gradient bytes
~100x is the point. The script shows the executable schedule switching
(the ``dgc_sparsity`` metric), compares against a dense-DP run, and
prints the per-step wire-byte estimate the sparse exchange implies.

Self-bootstraps a virtual 8-device CPU mesh when fewer than 8 devices
are present (the same recipe as tests/conftest.py), so it runs anywhere:

    python examples/dgc_dcn.py
"""

import argparse
import os
import subprocess
import sys


def _ensure_devices(n: int = 8) -> bool:
    """Re-exec on a virtual n-device CPU mesh if needed. Returns True in
    the child/ready process; the parent that delegated never returns —
    it raises SystemExit with the child's exit code."""
    import jax

    if len(jax.devices()) >= n or os.environ.get("_PTPU_DGC_CHILD") == "1":
        return True
    env = dict(os.environ)
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = \
        f"{flags} --xla_force_host_platform_device_count={n}".strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["_PTPU_DGC_CHILD"] = "1"
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import runpy, sys; sys.argv = [sys.argv[0]] + "
            f"{sys.argv[1:]!r}; "
            f"runpy.run_path({os.path.abspath(__file__)!r}, "
            "run_name='__main__')")
    raise SystemExit(subprocess.run(
        [sys.executable, "-c", code], env=env).returncode)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--sparsity", type=float, default=0.99)
    args = ap.parse_args()
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import mesh as M

    cfg = LlamaConfig.tiny(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, num_kv_heads=4, max_seq_len=64)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (16, 64)).astype(np.int32)

    def run(strategy, tag, optimizer):
        paddle_tpu.seed(7)
        model = LlamaForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optimizer, strategy=strategy, mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": jnp.asarray(ids),
                                      "labels": jnp.asarray(ids)})
            for i in range(args.steps):
                state, m = step(state, batch, jax.random.PRNGKey(i))
                sp = float(m.get("dgc_sparsity", -1.0))
                phase = ("dense" if sp == 0.0 else
                         f"sparse@{sp:.4g}" if sp > 0 else "dp")
                print(f"[{tag}] step {i:2d} loss={float(m['loss']):.4f} "
                      f"({phase})")
        return float(m["loss"])

    # DGC: 2 dense warmup steps, ramp over 4, then 99% sparse. DGC owns
    # the momentum — pair it with a plain-SGD outer optimizer.
    s = dist.DistributedStrategy()
    s.dgc.enable = True
    s.dgc.momentum = 0.9
    s.dgc.sparsity = (0.75, 0.9375, args.sparsity)
    s.dgc.rampup_begin_step = 2
    s.dgc.rampup_step = 4
    s.dgc.dense_size_threshold = 1024
    dgc_loss = run(s, "dgc", optim.SGD(3e-2))

    # dense-DP baseline with the equivalent Momentum optimizer
    dp_loss = run(dist.DistributedStrategy(), "dp",
                  optim.Momentum(3e-2, momentum=0.9))

    # wire-byte estimate at the final sparsity: each worker ships
    # (value, index) pairs for its top-k of every compressed tensor
    # instead of the dense fp32 gradient
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(
            jax.eval_shape(lambda: LlamaForCausalLM(cfg)))
        if hasattr(l, "shape") and l.size >= s.dgc.dense_size_threshold)
    dense_bytes = n_params * 4
    sparse_bytes = int(n_params * (1 - args.sparsity)) * 8
    print(f"\nfinal loss: dgc={dgc_loss:.4f} vs dense dp={dp_loss:.4f}")
    print(f"gradient wire bytes/step/worker (compressed tensors, "
          f"{n_params/1e3:.0f}k params): dense {dense_bytes/1e6:.2f} MB "
          f"-> dgc {sparse_bytes/1e6:.3f} MB "
          f"({dense_bytes / max(sparse_bytes, 1):.0f}x less)")


if __name__ == "__main__":
    if _ensure_devices():
        main()
