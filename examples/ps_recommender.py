"""CTR-style recommender on the parameter-server stack: embeddings live
in host-RAM sparse tables (C++), the dense tower trains on-device.

    python examples/ps_recommender.py [--steps 50] [--mode sync|async|geo]
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.distributed.ps import (
    Communicator, InProcClient, SparseEmbeddingHelper,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--mode", default="sync",
                    choices=["sync", "async", "geo"])
    args = ap.parse_args()

    paddle_tpu.seed(0)
    comm = Communicator(InProcClient(), args.mode)
    emb = SparseEmbeddingHelper(comm, "user_emb", 16,
                                optimizer="adagrad", lr=0.5,
                                init_scale=0.1, seed=1)
    tower = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))

    rs = np.random.RandomState(0)
    n_users = 1000
    labels_by_user = (rs.rand(n_users) > 0.5).astype(np.float32)

    @jax.jit
    def train_step(m, rows, inverse, y):
        def loss_fn(m, rows):
            logit = m(rows[inverse])[:, 0]
            return jnp.mean(jnp.maximum(logit, 0) - logit * y
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))
        loss, (gm, grows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(m, rows)
        m = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g, m, gm)
        return loss, m, grows

    for it in range(args.steps):
        ids = rs.randint(0, n_users, (64,))
        y = jnp.asarray(labels_by_user[ids])
        rows, inverse, uniq = emb.lookup(ids)
        loss, tower, grows = train_step(tower, rows, inverse, y)
        emb.apply_grads(uniq, grows)
        if it % 10 == 0 or it == args.steps - 1:
            print(f"step {it}: loss={float(loss):.4f} "
                  f"table_rows={comm.client.size('user_emb') if args.mode != 'geo' else 'local'}")
    comm.flush()
    comm.stop()


if __name__ == "__main__":
    main()
