"""Train → export → serve → query, end to end.

A classifier is trained eagerly, exported as a StableHLO artifact with
baked-in weights (``io.save_inference_model``), served by the TCP
``InferenceServer`` (the AnalysisPredictor/C-API serving analogue), and
queried from a client — the full deployment path.

    python examples/serve_model.py
"""

import argparse
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.io import (
    InferenceClient, InferenceServer, save_inference_model,
)
from paddle_tpu.nn import functional as F


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    args = ap.parse_args()

    # -- train a small classifier eagerly -------------------------------
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(8, 64), nn.LayerNorm(64), nn.ReLU(),
                        nn.Linear(64, 4))
    rs = np.random.RandomState(0)
    X = rs.randn(512, 8).astype(np.float32)
    Y = ((X[:, 0] > 0) + 2 * (X[:, 1] > 0)).astype(np.int32)

    opt = optim.AdamW(1e-2)
    opt_state = opt.init(net)

    @jax.jit
    def step(net, opt_state, x, y):
        loss, grads = jax.value_and_grad(
            lambda m: F.cross_entropy(m(x), y))(net)
        net, opt_state = opt.apply_gradients(net, grads, opt_state)
        return net, opt_state, loss

    loss = float("nan")
    for i in range(args.steps):
        net, opt_state, loss = step(net, opt_state, jnp.asarray(X),
                                    jnp.asarray(Y))
    acc = float(np.mean(
        np.argmax(np.asarray(net(jnp.asarray(X))), -1) == Y))
    print(f"trained: loss={float(loss):.4f} acc={acc:.3f}")

    # -- export + serve + query -----------------------------------------
    with tempfile.TemporaryDirectory(prefix="served_clf_") as tmp:
        path = f"{tmp}/clf"
        save_inference_model(path, net, [np.zeros((16, 8), np.float32)])

        server = InferenceServer({"clf": path}).start()
        print(f"serving 'clf' at {server.endpoint}")
        client = InferenceClient(server.endpoint)
        try:
            print("models:", {k: v["inputs"]
                              for k, v in client.list_models().items()})
            (logits,) = client.infer("clf", X[:16])
            preds = np.argmax(logits, -1)
            print("remote preds:", preds)
            assert (preds == Y[:16]).mean() > 0.8
            print("OK: remote predictions match training labels")
        finally:
            client.stop_server()
            client.close()


if __name__ == "__main__":
    main()
