"""Compiled autoregressive decoding: greedy, nucleus, and beam search
over the static KV cache — and the same loop on weight-only int8
(decode is HBM-bound; int8 weights halve the dominant traffic).

    python examples/generate_text.py
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import beam_search, generate
from paddle_tpu.quant import quantize_weights_int8


def main():
    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=128, num_layers=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, 128, (2, 8)).astype(np.int32))

    greedy = generate(model, prompt, 16)
    sampled = generate(model, prompt, 16, temperature=0.8, top_p=0.9,
                       key=jax.random.PRNGKey(7))
    beam = beam_search(model, prompt, 16, num_beams=4)
    print("greedy :", np.asarray(greedy[0]))
    print("sampled:", np.asarray(sampled[0]))
    print("beam   :", np.asarray(beam[0]))

    # weight-only int8: no calibration, same generate loop, half the
    # weight bytes per decoded token (~1% logits error)
    q = quantize_weights_int8(model)
    q_greedy = generate(q, prompt, 16)
    gen_from = prompt.shape[1]          # compare GENERATED tokens only
    agree = float(np.mean(np.asarray(q_greedy[:, gen_from:])
                          == np.asarray(greedy[:, gen_from:])))
    print(f"int8   : {np.asarray(q_greedy[0])}  "
          f"(generated-token agreement vs full-precision greedy: "
          f"{agree:.0%})")


if __name__ == "__main__":
    main()
