"""Long-context training with ring-attention sequence parallelism.

Trains a tiny Llama with the sequence axis sharded over 4 devices
(ring attention: K/V blocks rotate around the ring while each device
holds only T/4 of the sequence) and verifies the losses match a plain
data-parallel run — the correctness contract that lets the same config
scale to sequences no single chip could hold.

Self-bootstraps a virtual 8-device CPU mesh when fewer than 4 devices
are present (the same recipe as tests/conftest.py), so it runs anywhere:

    python examples/long_context_sp.py
"""

import argparse
import os
import subprocess
import sys


def _ensure_devices(n: int = 8) -> bool:
    """Re-exec on a virtual n-device CPU mesh if needed. Returns True in
    the child/ready process; the parent that delegated never returns —
    it raises SystemExit with the child's exit code."""
    import jax

    if len(jax.devices()) >= 4 or os.environ.get("_PTPU_SP_CHILD") == "1":
        return True
    env = dict(os.environ)
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = \
        f"{flags} --xla_force_host_platform_device_count={n}".strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["_PTPU_SP_CHILD"] = "1"
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import runpy, sys; sys.argv = [sys.argv[0]] + "
            f"{sys.argv[1:]!r}; "
            f"runpy.run_path({os.path.abspath(__file__)!r}, "
            "run_name='__main__')")
    raise SystemExit(subprocess.run(
        [sys.executable, "-c", code], env=env).returncode)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--seq", type=int, default=512)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import mesh as M

    cfg = LlamaConfig.tiny(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, num_kv_heads=4,
                           max_seq_len=args.seq)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, args.seq)).astype(np.int32)

    def run(strategy, tag):
        paddle_tpu.seed(7)
        model = LlamaForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-3), strategy=strategy,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": jnp.asarray(ids),
                                      "labels": jnp.asarray(ids)})
            losses = []
            for i in range(args.steps):
                state, m = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(m["loss"]))
        print(f"{tag}: axes={dict(mesh.shape)} losses="
              f"{[round(l, 4) for l in losses]}")
        return losses

    sp = dist.DistributedStrategy()
    sp.sequence_parallel.enable = True
    sp.sequence_parallel.degree = 4
    sp.sequence_parallel.mode = "ring"
    ring = run(sp, "ring sp=4")
    ref = run(dist.DistributedStrategy(), "plain dp ")
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-5)
    print(f"OK: ring-attention losses match dense attention over "
          f"{args.steps} steps at seq {args.seq}")


if __name__ == "__main__":
    if _ensure_devices():
        main()
