"""Train a Llama-family decoder with a composed distributed strategy.

Usage (defaults to a tiny smoke config on whatever devices exist):
    python examples/train_llama.py [--steps 20] [--smoke]
Scale up by editing the config/strategy — the same script drives 7B on a
pod slice (see README quickstart).
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    paddle_tpu.seed(0)
    n_dev = len(jax.devices())
    cfg = (LlamaConfig.tiny() if args.smoke or n_dev == 1
           else LlamaConfig(hidden_size=2048, intermediate_size=5632,
                            num_layers=16, num_heads=16, num_kv_heads=16,
                            max_seq_len=2048))
    strategy = dist.DistributedStrategy()
    if n_dev > 1:
        strategy.sharding.enable = True
        strategy.sharding.stage = 3
        strategy.sharding.degree = n_dev

    model = LlamaForCausalLM(cfg)
    mesh = M.mesh_from_strategy(strategy)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(3e-4), strategy=strategy,
            mesh=mesh)
        state = step.init_state(model)
        bs = max(4, 2 * n_dev)
        seq = 64 if args.smoke else min(cfg.max_seq_len, 2048)
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (bs, seq)).astype(np.int32)
        batch = step.shard_batch({"input_ids": jnp.asarray(ids),
                                  "labels": jnp.asarray(ids)})
        for i in range(args.steps):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            if i % 5 == 0 or i == args.steps - 1:
                print(f"step {i}: loss={float(metrics['loss']):.4f} "
                      f"grad_norm={float(metrics['grad_norm']):.3f}")


if __name__ == "__main__":
    main()
