"""Elastic training that survives NODE loss, not just process loss.

The reference persists auto-checkpoint state to HDFS keyed by job id
(``fluid/incubate/checkpoint/auto_checkpoint.py``, ``fleet/utils/fs.py``)
so a restarted pod resumes instead of redoing. The paddle_tpu analogue:
point ``TrainEpochRange`` at a REMOTE checkpoint URL (``io.fs`` scheme —
here the built-in ``ptfs://`` TCP filesystem, in production a storage
node or any ``register_fs``-registered backend). Saves stage locally and
upload the completed step; a relaunched trainer on a FRESH machine
(empty staging cache) pulls the latest complete step and fast-forwards.

This script plays all three roles in one process:
1. a "storage node" (FSService rooted in a temp dir),
2. trainer run A: trains half the epochs, saving through ptfs://,
3. trainer run B: simulates node loss (wipes run A's staging cache +
   uses a different cache root), resumes from the remote, finishes.

Run: python examples/elastic_remote_ckpt.py [--epochs 6 --steps 20]
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--steps", type=int, default=20, help="steps/epoch")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.io import FSService, TrainEpochRange
    from paddle_tpu.io import checkpoint as ckpt
    from paddle_tpu.nn import functional as F

    work = tempfile.mkdtemp(prefix="elastic_demo_")
    storage = os.path.join(work, "storage_node")
    caches = [os.path.join(work, "node_a_cache"),
              os.path.join(work, "node_b_cache")]

    # --- the storage node: any box reachable over TCP ------------------
    srv = FSService(storage).start()
    url = f"ptfs://{srv.endpoint}/demo-job"
    print(f"storage node serving {storage!r} at {url}")

    # --- a tiny classification task ------------------------------------
    rs = np.random.RandomState(0)
    Xn = rs.randn(256, 16).astype(np.float32)
    X = jnp.asarray(Xn)
    Y = jnp.asarray((Xn[:, 0] > 0).astype(np.int32))   # learnable target

    def make_state():
        paddle_tpu.seed(0)
        net = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 2))
        opt = optim.AdamW(1e-2)
        return {"net": net, "opt": opt.init(net)}, opt

    def train(node: int, epochs: int, label: str):
        """One trainer lifetime on "node_<node>" (its own staging
        cache, as a distinct machine would have)."""
        os.environ["PADDLE_JOB_ID"] = "demo-job-42"   # shared identity
        # per-node staging location (each real machine has its own);
        # reset_remote_cache() plays the process restart
        os.environ["PADDLE_CKPT_CACHE_ROOT"] = caches[node]
        ckpt.reset_remote_cache()
        state, opt = make_state()

        @jax.jit
        def step(state):
            def loss_fn(net):
                return F.cross_entropy(net(X), Y)
            loss, g = jax.value_and_grad(loss_fn)(state["net"])
            net, ostate = opt.apply_gradients(state["net"], g,
                                              state["opt"])
            return {"net": net, "opt": ostate}, loss

        r = TrainEpochRange(epochs, url, state=state, save_interval=1)
        print(f"[{label}] resumed={r.resumed} start_epoch={r.start_epoch}")
        loss = float("nan")
        for epoch in r:
            s = r.state
            for _ in range(args.steps):
                s, loss = step(s)
            r.state = s
            print(f"[{label}] epoch {epoch}: loss={float(loss):.4f}")
        r.flush()
        return r

    try:
        # --- run A: completes half the job, then the "node dies" ------
        half = max(args.epochs // 2, 1)
        train(0, half, "node A")
        shutil.rmtree(caches[0], ignore_errors=True)  # node A is GONE
        from paddle_tpu.io import fs as fs_mod
        probe = fs_mod.fs_for_path(url)
        surviving = probe.ls_dir(url)[0]
        probe.close()
        print(f"node A lost (staging cache wiped); remote step dirs "
              f"survive on the storage node: {surviving}")

        # --- run B: fresh machine, empty cache — resumes remotely -----
        r = train(1, args.epochs, "node B")
        assert r.resumed and r.start_epoch == half, (r.resumed,
                                                     r.start_epoch)
        print(f"node B resumed at epoch {r.start_epoch} from {url} and "
              f"finished the job — elastic across node loss")
    finally:
        ckpt.reset_remote_cache()
        srv.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
